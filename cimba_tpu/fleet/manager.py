"""Fleet lifecycle: spawn slices, watch them, replace the dead ones.

:class:`FleetManager` is the one-call deployment of the multi-process
serving fleet (docs/20_fleet.md): it spawns ``n_slices`` slice worker
processes (``python -m cimba_tpu.fleet.slice``), reads each one's
ready line, registers them with a :class:`~cimba_tpu.fleet.router.
FleetRouter` and a :class:`~cimba_tpu.fleet.health.HealthPoller`, and
— when the poller marks a slice down — reaps the corpse and spawns a
warm replacement (the new process inherits ``CIMBA_PROGRAM_STORE``, so
it hydrates compiled programs from the store manifest and serves its
first request without compiling; PR 6's sub-second slice replacement).

    from cimba_tpu.fleet.manager import FleetManager
    models = {"mm1": {"fn": "cimba_tpu.models.mm1:build",
                      "kwargs": {"record": False}}}
    with FleetManager(models, n_slices=2, store=store_dir) as fm:
        h = fm.router.submit(serve.Request(fm.spec("mm1"), params, 64))
        result = h.result()

The manager resolves the SAME model builders the slices run
(:func:`~cimba_tpu.fleet.slice.load_models`), so ``fm.spec(name)`` is
the spec object clients put in their Requests and the router's
registry resolves it by structural fingerprint.  Everything here is
host-side process plumbing — importing ``cimba_tpu`` (or even this
module) spawns nothing; only constructing a manager does.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from typing import Any, Dict, Optional

from cimba_tpu.fleet.health import HealthPoller
from cimba_tpu.fleet.router import FleetRouter, SliceHandle
from cimba_tpu.fleet.slice import load_models

__all__ = ["FleetManager", "SliceSpawnError"]


class SliceSpawnError(RuntimeError):
    """A slice process failed to produce its ready line."""


def _read_ready(proc: subprocess.Popen, timeout: float) -> dict:
    """Read the slice's one-line ready JSON from stdout with a
    timeout (a thread — readline has no native timeout)."""
    box: Dict[str, Any] = {}

    def read():
        box["line"] = proc.stdout.readline()

    t = threading.Thread(target=read, daemon=True)
    t.start()
    t.join(timeout)
    line = box.get("line", "")
    if not line:
        rc = proc.poll()
        raise SliceSpawnError(
            f"slice produced no ready line within {timeout}s "
            f"(exit code {rc}); see its stderr"
        )
    try:
        return json.loads(line)
    except json.JSONDecodeError as e:
        raise SliceSpawnError(
            f"unparseable slice ready line {line!r}"
        ) from e


class FleetManager:
    """Spawn + supervise a fleet of slice processes behind one router.

    ``models`` is the registry both sides build from (see module
    docstring); ``store`` (optional) is a program-store root exported
    to every slice as ``CIMBA_PROGRAM_STORE`` (+ ``warm_chunk_steps``
    naming the store entry's chunk budget); ``slice_env`` maps the
    INITIAL slice index to extra env vars (the chaos-injection hook:
    ``{1: {"CIMBA_FLEET_CHAOS": "seed=7,kill=20"}}``) — replacements
    spawn with the base env only, so a chaos-killed slice is replaced
    by a healthy one.  ``respawn=False`` disables replacement (a test
    watching a hole stay open).

    The fleet observability plane (docs/23_fleet_observability.md),
    all None-default and zero-cost off: ``telemetry`` attaches the
    router's span/metric/healthz plane; ``expose_port`` additionally
    serves it (``/metrics`` + ``/healthz`` + ``/varz`` on loopback,
    ``0`` = ephemeral — read ``manager.expose.url``); ``span_dir``
    exports ``CIMBA_FLEET_TELEMETRY`` to every slice so each writes
    ``<span_dir>/<slice>.spans.jsonl`` and grafts its spans under the
    router's wire spans; ``capacity_placement`` forwards to
    :class:`~cimba_tpu.fleet.router.FleetRouter`."""

    def __init__(
        self,
        models: Dict[str, Any],
        n_slices: int = 2,
        *,
        max_wave: int = 4096,
        max_pending: int = 64,
        window: int = 4,
        store: Optional[str] = None,
        warm_chunk_steps: Optional[int] = None,
        poll_interval: float = 0.5,
        scrape_timeout: float = 1.0,
        respawn: bool = True,
        slice_env: Optional[Dict[int, Dict[str, str]]] = None,
        place_seed: int = 0,
        max_requeues: int = 8,
        request_timeout: Optional[float] = 600.0,
        spawn_timeout: float = 180.0,
        horizon_bucket: Optional[float] = 16.0,
        name: str = "cimba-fleet",
        telemetry=None,
        expose_port: Optional[int] = None,
        span_dir: Optional[str] = None,
        capacity_placement: Optional[bool] = None,
    ):
        if n_slices <= 0:
            raise ValueError(f"n_slices must be positive: {n_slices}")
        if expose_port is not None and telemetry is None:
            raise ValueError(
                "expose_port needs a telemetry plane to serve — pass "
                "telemetry= as well (docs/23_fleet_observability.md)"
            )
        self.models_json = json.dumps(
            models if not isinstance(models, str) else json.loads(models)
        )
        self._specs = load_models(models)
        self.store = store
        self.warm_chunk_steps = warm_chunk_steps
        self.max_wave = int(max_wave)
        self.max_pending = int(max_pending)
        self._horizon_bucket = horizon_bucket
        self.poll_interval = float(poll_interval)
        self.respawn = bool(respawn)
        self.spawn_timeout = float(spawn_timeout)
        self._closing = False
        self._n = 0
        self._lock = threading.Lock()
        self.telemetry = telemetry
        self.span_dir = span_dir
        self.router = FleetRouter(
            models=self._specs, window=window, place_seed=place_seed,
            max_requeues=max_requeues, request_timeout=request_timeout,
            horizon_bucket=horizon_bucket, name=name,
            telemetry=telemetry, capacity_placement=capacity_placement,
        )
        self.expose = None
        if expose_port is not None:
            from cimba_tpu.obs import expose as _expose

            self.expose = _expose.start(telemetry, port=expose_port)
        procs = []
        try:
            for i in range(n_slices):
                procs.append(self._launch(
                    extra_env=(slice_env or {}).get(i)
                ))
            for proc, sname in procs:
                self._register(proc, sname)
        except BaseException:
            for proc, _ in procs:
                proc.kill()
            if self.expose is not None:
                self.expose.close()
            raise
        self.poller = HealthPoller(
            self.router, interval=self.poll_interval,
            timeout=scrape_timeout, on_down=self._on_down,
        )

    # -- the spawn leg -------------------------------------------------------

    def spec(self, name: str):
        """The parent-side spec object for ``name`` — what client
        Requests must carry so the router resolves them."""
        return self._specs[name]

    def _launch(self, extra_env: Optional[Dict[str, str]] = None):
        with self._lock:
            sname = f"slice{self._n}"
            self._n += 1
        cmd = [
            sys.executable, "-m", "cimba_tpu.fleet.slice",
            "--name", sname,
            "--models", self.models_json,
            "--port", "0",
            "--health-port", "0",
            "--max-wave", str(self.max_wave),
            "--max-pending", str(self.max_pending),
            # the router's co-location class and the slice's packing
            # class share one definition — and one RATIO
            "--horizon-bucket", (
                "none" if self._horizon_bucket is None
                else repr(float(self._horizon_bucket))
            ),
        ]
        if self.warm_chunk_steps is not None:
            cmd += ["--warm-chunk-steps", str(self.warm_chunk_steps)]
        env = dict(os.environ)
        if self.store is not None:
            env["CIMBA_PROGRAM_STORE"] = str(self.store)
        if self.span_dir is not None:
            # every slice (replacements too) writes
            # <span_dir>/<name>.spans.jsonl and grafts its spans under
            # the router's wire spans via the run headers' trace
            # context (docs/23_fleet_observability.md)
            env["CIMBA_FLEET_TELEMETRY"] = str(self.span_dir)
        env.update(extra_env or {})
        proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=None, text=True,
            env=env,
        )
        return proc, sname

    def _register(self, proc: subprocess.Popen, sname: str) -> SliceHandle:
        try:
            info = _read_ready(proc, self.spawn_timeout)
        except SliceSpawnError:
            proc.kill()
            raise
        handle = SliceHandle(
            sname, "127.0.0.1", info["port"], info["url"],
            proc=proc, pid=info.get("pid"),
        )
        self.router.add_slice(handle)
        return handle

    def _spawn(self, extra_env: Optional[Dict[str, str]] = None
               ) -> SliceHandle:
        proc, sname = self._launch(extra_env)
        return self._register(proc, sname)

    def _on_down(self, handle: SliceHandle, reason: str) -> None:
        """Poller callback: hand the reap + respawn to a worker thread
        and return immediately — a replacement's startup (process
        spawn, jax import, store hydrate) takes seconds, and blocking
        the ONLY polling thread that long would leave a second
        near-simultaneous death undetected, violating the
        one-poll-interval contract."""
        threading.Thread(
            target=self._replace, args=(handle,),
            name=f"fleet-respawn-{handle.name}", daemon=True,
        ).start()

    def _replace(self, handle: SliceHandle) -> None:
        proc = handle.proc
        if proc is not None:
            if proc.poll() is None:
                # marked down but still running (stalled dispatcher,
                # unscrapeable): a down slice gets no more placements,
                # so keeping the process is pure waste
                proc.kill()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass  # unreapable zombie; init will collect it
            if proc.stdout is not None:
                proc.stdout.close()
        # forget the corpse entirely — a long kill/respawn churn must
        # not accumulate dead handles in the router's placement scans
        self.router.remove_slice(handle.name)
        if self.respawn and not self._closing:
            try:
                h = self._spawn()
                if self._closing and h.proc is not None:
                    # shutdown raced the respawn: don't leave an
                    # orphan (the slice's own parent-gone watchdog is
                    # the backstop, this is the prompt path)
                    h.proc.kill()
            except SliceSpawnError:
                # the poller's transitions already record the death;
                # a failed respawn must not kill the worker silently —
                # surface it where slice logs go
                import traceback

                traceback.print_exc()

    # -- observability -------------------------------------------------------

    def fleet_manifest(self) -> dict:
        """The fleet as ``tools/metrics_dump.py --fleet`` consumes it:
        ``{"slices": [{"name", "url", "up"}]}``."""
        return {
            "slices": [
                {"name": h.name, "url": h.health_url, "up": h.up}
                for h in self.router.slices().values()
            ]
        }

    def stats(self) -> dict:
        out = self.router.stats()
        out["health"] = self.poller.reports()
        return out

    # -- lifecycle -----------------------------------------------------------

    def shutdown(self, wait: bool = True,
                 timeout: Optional[float] = None) -> None:
        self._closing = True
        self.poller.close()
        self.router.shutdown(wait=wait, timeout=timeout)
        if self.expose is not None:
            self.expose.close()
        for h in self.router.slices().values():
            proc = h.proc
            if proc is None:
                continue
            if proc.poll() is None:
                proc.terminate()
        deadline = time.monotonic() + 10.0
        for h in self.router.slices().values():
            proc = h.proc
            if proc is None:
                continue
            try:
                proc.wait(timeout=max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10)
            if proc.stdout is not None:
                proc.stdout.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(wait=False)
