"""Health-scrape failover: the fleet's liveness loop.

A poller thread scrapes every slice's ``/healthz`` and ``/metrics``
(the PR 8 exposition plane, parsed by the one in-repo
``parse_prometheus_text``) on a fixed interval.  A slice is marked
**down within one poll interval** of any of: its process no longer
answering HTTP (kill -9, crash), a 503 ``/healthz`` (dead or stalled
dispatcher), or a scrape exceeding the timeout (the chaos
``scrape_delay_ms`` arm).  Marking down is a call into
:meth:`~cimba_tpu.fleet.router.FleetRouter.mark_down` — the slice's
queued and in-flight requests requeue onto live slices with the slice
id appended to their ``excluded`` set (the ``serve/sched.py``
solo-retry pattern lifted one level) — followed by the ``on_down``
callback the :class:`~cimba_tpu.fleet.manager.FleetManager` uses to
respawn a replacement.

Healthy scrapes feed the router's placement: queue depth, outstanding,
padding waste, the program-store hit/fallback counters, and the
capacity plane (live lane occupancy, the refill wave's free-lane pool)
land in each handle's ``scraped`` dict (and in
:meth:`HealthPoller.reports`), which is what
``tools/metrics_dump.py --fleet`` tabulates, what capacity-aware
placement ranks by, and — via the scrape's parsed ``families`` — what
the router federates into one fleet ``/metrics``
(docs/23_fleet_observability.md).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional

__all__ = ["HealthPoller", "scrape_slice"]


def scrape_slice(health_url: str, timeout: float) -> dict:
    """One scrape of one slice: ``/healthz`` verdict + the placement
    gauges parsed out of ``/metrics``.  Returns a report dict with
    ``reachable``/``verdict`` always present; raises nothing (an
    unreachable endpoint IS the signal)."""
    from cimba_tpu.obs.expose import parse_prometheus_text

    base = health_url.rstrip("/")
    out: dict = {
        "reachable": False,
        "verdict": "unreachable",
        "t": time.monotonic(),
    }
    try:
        try:
            with urllib.request.urlopen(
                base + "/healthz", timeout=timeout
            ) as r:
                body = r.read()
                status = r.status
        except urllib.error.HTTPError as e:
            body = e.read()
            status = e.code
        hz = json.loads(body)
        out["reachable"] = True
        out["http_status"] = status
        out["verdict"] = hz.get("status", "unhealthy")
        with urllib.request.urlopen(
            base + "/metrics", timeout=timeout
        ) as r:
            text = r.read().decode()
        samples = parse_prometheus_text(text)["samples"]

        def total(name):
            fam = samples.get(name)
            if not fam:
                return None
            return sum(fam.values())

        for field, metric in (
            ("queue_depth", "cimba_serve_queue_depth"),
            ("outstanding", "cimba_serve_outstanding"),
            ("padding_waste", "cimba_serve_padding_waste_ratio"),
            ("completed", "cimba_serve_requests_completed_total"),
            ("store_hits", "cimba_program_store_hits_total"),
            ("store_fallback_shapes",
             "cimba_program_store_fallback_shapes_total"),
            # the capacity plane (docs/23_fleet_observability.md):
            # live occupancy + the refill wave's free-lane pool — what
            # the router's capacity-aware placement ranks by
            ("occupancy_now", "cimba_serve_lane_occupancy_now"),
            ("occupancy_mean", "cimba_serve_lane_occupancy_mean"),
            ("free_lanes", "cimba_serve_free_lanes"),
            ("refill_enabled", "cimba_serve_refill_enabled"),
            ("refill_admissions", "cimba_serve_refill_admissions_total"),
            ("lanes_refilled", "cimba_serve_lanes_refilled_total"),
            # the device-scheduler plane (docs/24_device_scheduler.md):
            # concurrent live waves + estimated free device memory —
            # the memory-side capacity signal next to free_lanes — and
            # the preempt/restore churn counters
            ("waves_live", "cimba_serve_waves_live"),
            ("preemptions", "cimba_serve_preemptions_total"),
            ("restores", "cimba_serve_restores_total"),
            ("est_free_mem",
             "cimba_serve_est_free_device_mem_bytes"),
        ):
            v = total(metric)
            if v is not None:
                out[field] = v
        # the whole parsed scrape, one number per family (labels
        # summed) — what the router federates into the fleet registry
        # as {family}{slice=...} gauges + a slice="all" rollup.
        # Histogram le-buckets are cumulative and don't sum.
        out["families"] = {
            fname: sum(series.values())
            for fname, series in samples.items()
            if not fname.endswith("_bucket")
        }
        # the per-tenant QoS view (docs/27_qos.md): the flattened
        # families above sum the tenant label away, so the tenant
        # detail rides its own field — {tenant: {family: value}} over
        # the cimba_serve_qos_* families (tenant-labeled by
        # construction), summed across services within the slice.
        # ``metrics_dump --fleet`` and the router's tenant federation
        # read this.
        tenants: dict = {}
        for fname, series in samples.items():
            if not fname.startswith("cimba_serve_qos_") \
                    or fname.endswith("_bucket"):
                continue
            for labels, val in series.items():
                tname = dict(labels).get("tenant")
                if tname is None:
                    continue
                row = tenants.setdefault(tname, {})
                row[fname] = row.get(fname, 0.0) + float(val)
        if tenants:
            out["tenants"] = tenants
    except (OSError, ValueError) as e:
        # connection refused/reset, timeout, or unparseable body —
        # all of them mean "treat this slice as gone"
        out["error"] = f"{type(e).__name__}: {e}"
    return out


class HealthPoller:
    """The fleet's background scrape loop over a
    :class:`~cimba_tpu.fleet.router.FleetRouter`'s slices.

    ``interval`` is the poll period — the failover-latency contract is
    "a dead slice is marked down within one interval (plus the scrape
    ``timeout``)".  ``on_down(handle, reason)`` runs AFTER the router
    requeued the slice's in-flight requests (the manager's respawn
    hook).  ``transitions`` records ``(t, slice, event, reason)`` rows
    for tests and post-mortems."""

    # cimba-check: must-hold(_lock) transitions, _reports, _down_seen

    def __init__(
        self,
        router,
        *,
        interval: float = 0.5,
        timeout: float = 1.0,
        on_down: Optional[Callable] = None,
        autostart: bool = True,
    ):
        self.router = router
        self.interval = float(interval)
        self.timeout = float(timeout)
        self.on_down = on_down
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.transitions: List[tuple] = []
        self._reports: Dict[str, dict] = {}
        self._down_seen: set = set()
        self._thread: Optional[threading.Thread] = None
        if autostart:
            self.start()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="cimba-fleet-health", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            self.poll_once()

    def poll_once(self) -> None:
        """One pass over every registered slice (also callable
        synchronously from tests).  A slice the ROUTER already marked
        down passively (connection refused mid-request) is picked up
        here too — the transition is recorded at the router's flip
        time and ``on_down`` still fires exactly once per death."""
        for name, handle in self.router.slices().items():
            if not handle.up:
                self._handle_down(
                    handle,
                    handle.down_reason or "marked down",
                    at=handle.down_t,
                )
                continue
            rep = scrape_slice(handle.health_url, self.timeout)
            with self._lock:
                self._reports[name] = rep
            if not rep["reachable"] or rep["verdict"] == "unhealthy":
                reason = rep.get(
                    "error", f"healthz {rep['verdict']}"
                )
                self.router.mark_down(name, reason)
                self._handle_down(handle, reason)
            else:
                self.router.update_scrape(name, rep)

    def _handle_down(self, handle, reason: str,
                     at: Optional[float] = None) -> None:
        """Record one slice's death exactly once and fire ``on_down``."""
        with self._lock:
            if handle.name in self._down_seen:
                return
            self._down_seen.add(handle.name)
            self.transitions.append(
                (at if at is not None else time.monotonic(),
                 handle.name, "down", reason)
            )
        if self.on_down is not None:
            try:
                self.on_down(handle, reason)
            except Exception as e:
                # a respawn hook bug must not kill the poller (the
                # fleet would silently stop failing over)
                with self._lock:
                    self.transitions.append((
                        time.monotonic(), handle.name,
                        "on_down_error", repr(e),
                    ))

    def reports(self) -> Dict[str, dict]:
        with self._lock:
            return dict(self._reports)

    def close(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None
