"""Synthetic load drivers for the experiment service.

The serving claim worth measuring is not one request's latency but the
distribution under concurrent load: N client threads submitting
requests against the bounded queue, open-loop (arrivals on a fixed
schedule, independent of completions — the shape that exposes queueing
collapse) or as a burst.  This module is the shared driver behind
``examples/serve_mm1.py``, the bench serve arm, and the many-client
soak test — host-side threading only, no jax.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Sequence

from cimba_tpu.serve.sched import RetryAfter


def percentile(xs: Sequence[float], q: float) -> float:
    """Nearest-rank percentile (q in [0, 100]) — dependency-free and
    exact on the small sample counts a load run produces."""
    if not xs:
        return float("nan")
    s = sorted(xs)
    k = max(0, min(len(s) - 1, int(round(q / 100.0 * (len(s) - 1)))))
    return s[k]


@dataclass
class LoadReport:
    """What a load run measured.  ``latencies_s`` is submit→result wall
    time per COMPLETED request; structured failures are counted by
    class, never silently dropped."""

    n_requests: int
    n_completed: int
    wall_s: float
    total_replications: int
    latencies_s: List[float] = field(default_factory=list)
    errors: dict = field(default_factory=dict)
    results: list = field(default_factory=list)
    #: submit→result latency keyed by request index (completed only) —
    #: what lets a mixed-template run attribute latency per template
    latency_by_index: dict = field(default_factory=dict)
    #: request index -> template name, set by :func:`run_mixed_load`
    template_names: Optional[List[str]] = None
    #: request index -> tenant id (None = default), set by
    #: :func:`run_load` from the requests' own ``tenant`` fields
    tenant_names: Optional[List[str]] = None
    #: structured RetryAfter throttles observed at submit, by tenant
    #: (docs/27_qos.md) — every sleep-and-retry counts, so the flood
    #: pressure a QoS policy absorbed is visible, not hidden by retries
    throttles_by_tenant: dict = field(default_factory=dict)

    @property
    def replications_per_sec(self) -> float:
        return self.total_replications / self.wall_s if self.wall_s else 0.0

    def latency_percentiles(self) -> dict:
        return {
            "p50_s": percentile(self.latencies_s, 50),
            "p95_s": percentile(self.latencies_s, 95),
            "p99_s": percentile(self.latencies_s, 99),
            "max_s": max(self.latencies_s) if self.latencies_s else
            float("nan"),
        }

    def summary(self) -> dict:
        out = {
            "requests": self.n_requests,
            "completed": self.n_completed,
            "wall_s": self.wall_s,
            "replications_per_sec": self.replications_per_sec,
            "errors": dict(self.errors),
        }
        if self.throttles_by_tenant:
            out["throttles"] = sum(self.throttles_by_tenant.values())
        out.update(self.latency_percentiles())
        return out

    def per_template(self) -> dict:
        """Latency percentiles grouped by template name (requires the
        run to have come through :func:`run_mixed_load`, which records
        ``template_names``): ``{name: {count, completed, p50_s, p95_s,
        p99_s, max_s}}`` — the per-template tail is where a packing
        policy's fairness shows (a starved template's p99 diverges
        while the aggregate looks fine)."""
        if self.template_names is None:
            raise ValueError(
                "per_template() needs template_names — drive the load "
                "with run_mixed_load(), not run_load()"
            )
        groups: dict = {}
        for i, name in enumerate(self.template_names):
            g = groups.setdefault(
                name, {"count": 0, "completed": 0, "lat": []}
            )
            g["count"] += 1
            if i in self.latency_by_index:
                g["completed"] += 1
                g["lat"].append(self.latency_by_index[i])
        out = {}
        for name, g in groups.items():
            lat = g["lat"]
            out[name] = {
                "count": g["count"],
                "completed": g["completed"],
                "p50_s": percentile(lat, 50),
                "p95_s": percentile(lat, 95),
                "p99_s": percentile(lat, 99),
                "max_s": max(lat) if lat else float("nan"),
            }
        return out

    def per_tenant(self) -> dict:
        """Latency percentiles, goodput, and throttle counts grouped
        by tenant (docs/27_qos.md): ``{tenant: {count, completed,
        goodput, throttled, p50_s, p95_s, p99_s, max_s}}``.  The
        per-tenant tail is the QoS claim itself — under a flooding
        tenant, the victims' p99/goodput here is what the fair-share
        scheduler protects (the aggregate hides it)."""
        if self.tenant_names is None:
            raise ValueError(
                "per_tenant() needs tenant_names — drive the load "
                "with run_load()/run_mixed_load()"
            )
        groups: dict = {}
        for i, name in enumerate(self.tenant_names):
            g = groups.setdefault(
                name or "default", {"count": 0, "completed": 0, "lat": []}
            )
            g["count"] += 1
            if i in self.latency_by_index:
                g["completed"] += 1
                g["lat"].append(self.latency_by_index[i])
        out = {}
        for name, g in groups.items():
            lat = g["lat"]
            out[name] = {
                "count": g["count"],
                "completed": g["completed"],
                "goodput": (
                    g["completed"] / g["count"] if g["count"] else 0.0
                ),
                "throttled": self.throttles_by_tenant.get(name, 0),
                "p50_s": percentile(lat, 50),
                "p95_s": percentile(lat, 95),
                "p99_s": percentile(lat, 99),
                "max_s": max(lat) if lat else float("nan"),
            }
        return out


def run_load(
    service,
    requests: Sequence[Any],
    *,
    n_clients: int = 1,
    inter_arrival_s: float = 0.0,
    submit_block: bool = True,
    submit_timeout: Optional[float] = None,
    result_timeout: Optional[float] = None,
    on_result: Optional[Callable] = None,
    max_retry_after: int = 8,
) -> LoadReport:
    """Drive ``service`` with ``requests`` from ``n_clients`` threads.

    Open-loop: request i's arrival time is ``t0 + i * inter_arrival_s``
    regardless of completions (``inter_arrival_s=0`` is a burst).
    Clients pull the next scheduled arrival off a shared cursor, sleep
    until its time, submit, and immediately move on — a second pass
    collects every future, so slow results never throttle arrivals.
    Admission rejects (``QueueFull``) and structured failures are
    counted per error class in the report.  A structured
    :class:`~cimba_tpu.serve.sched.RetryAfter` throttle is HONORED
    (docs/27_qos.md): the client sleeps exactly the server's
    ``delay_s`` and resubmits, up to ``max_retry_after`` times per
    request before counting it as an error — every throttle is tallied
    per tenant in ``throttles_by_tenant``.  ``results`` keeps completed
    ``(index, StreamResult)`` pairs in arrival order for correctness
    checks (``on_result(i, res)`` streams them instead when holding all
    results would be too much)."""
    t0 = time.perf_counter()
    cursor = [0]
    lock = threading.Lock()
    handles: List[Optional[tuple]] = [None] * len(requests)
    errors: dict = {}
    throttles: dict = {}

    def client():
        while True:
            with lock:
                i = cursor[0]
                if i >= len(requests):
                    return
                cursor[0] += 1
            due = t0 + i * inter_arrival_s
            delay = due - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            sub_t = time.perf_counter()
            sub_mono = time.monotonic()
            attempts = 0
            while True:
                try:
                    h = service.submit(
                        requests[i], block=submit_block,
                        timeout=submit_timeout,
                    )
                except RetryAfter as e:
                    with lock:
                        throttles[e.tenant] = (
                            throttles.get(e.tenant, 0) + 1
                        )
                    attempts += 1
                    if attempts > max_retry_after:
                        with lock:
                            errors["RetryAfter"] = (
                                errors.get("RetryAfter", 0) + 1
                            )
                        break
                    time.sleep(e.delay_s)
                    continue
                except Exception as e:
                    with lock:
                        errors[type(e).__name__] = (
                            errors.get(type(e).__name__, 0) + 1
                        )
                    break
                handles[i] = (sub_t, sub_mono, h)
                break

    threads = [
        threading.Thread(target=client, daemon=True)
        for _ in range(max(1, n_clients))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    latencies: List[float] = []
    latency_by_index: dict = {}
    results: list = []
    n_completed = 0
    total_reps = 0
    for i, rec in enumerate(handles):
        if rec is None:
            continue
        sub_t, sub_mono, h = rec
        try:
            res = h.result(timeout=result_timeout)
        except Exception as e:
            errors[type(e).__name__] = errors.get(type(e).__name__, 0) + 1
            continue
        # DELIVERY latency, not collection latency: the dispatcher's
        # monotonic finish stamp against this request's monotonic
        # submit stamp.  The sequential collection pass here can reach
        # a long-resolved future arbitrarily late (e.g. while other
        # client threads sit in RetryAfter sleeps) — the wall-clock
        # fallback only covers handles without the stamp.
        ft = getattr(h, "finish_t", None)
        lat = (
            ft - sub_mono if ft is not None
            else time.perf_counter() - sub_t
        )
        latencies.append(lat)
        latency_by_index[i] = lat
        n_completed += 1
        total_reps += int(requests[i].n_replications)
        if on_result is not None:
            on_result(i, res)
        else:
            results.append((i, res))
    return LoadReport(
        n_requests=len(requests),
        n_completed=n_completed,
        wall_s=time.perf_counter() - t0,
        total_replications=total_reps,
        latencies_s=latencies,
        errors=errors,
        results=results,
        latency_by_index=latency_by_index,
        tenant_names=[
            getattr(r, "tenant", None) for r in requests
        ],
        throttles_by_tenant=throttles,
    )


# -- mixed-template traffic (the heterogeneous-packing load shape) -----------


@dataclass(frozen=True)
class RequestTemplate:
    """One request archetype in a traffic mix: a prototype ``Request``
    (spec variant x params x R x seed x horizon — whatever the
    workload's shape is) plus its relative ``weight`` in the arrival
    stream.  :func:`mixed_requests` interleaves templates
    proportionally; each instance is a ``dataclasses.replace`` clone
    labelled ``{name}#{i}``.  ``tenant`` (docs/27_qos.md) stamps every
    instance with a tenant id — how an adversarial mix puts a flooding
    tenant and its victims through one service."""

    name: str
    request: Any
    weight: float = 1.0
    tenant: Optional[str] = None


def mixed_requests(
    templates: Sequence[RequestTemplate], n_requests: int,
) -> tuple:
    """A deterministic weighted interleaving of ``n_requests`` request
    instances over ``templates`` (smooth weighted round-robin: each
    step picks the template with the largest accumulated credit, so a
    1:1:2 mix arrives interleaved — the shape that exercises wave
    packing — rather than in runs).  Returns ``(requests, names)``
    aligned by index."""
    import dataclasses

    if not templates:
        raise ValueError("mixed_requests needs at least one template")
    for t in templates:
        if not t.weight > 0:
            raise ValueError(
                f"template {t.name!r} weight must be positive, got "
                f"{t.weight}"
            )
    credit = [0.0] * len(templates)
    counts = [0] * len(templates)
    requests, names = [], []
    for _ in range(int(n_requests)):
        for j, t in enumerate(templates):
            credit[j] += t.weight
        j = max(range(len(templates)), key=lambda k: credit[k])
        credit[j] -= sum(t.weight for t in templates)
        t = templates[j]
        kw = {"label": f"{t.name}#{counts[j]}"}
        if t.tenant is not None:
            kw["tenant"] = t.tenant
        requests.append(dataclasses.replace(t.request, **kw))
        names.append(t.name)
        counts[j] += 1
    return requests, names


def run_mixed_load(
    service,
    templates: Sequence[RequestTemplate],
    n_requests: int,
    **run_load_kwargs,
) -> LoadReport:
    """Drive ``service`` with a weighted MIX of request templates (the
    heterogeneous-traffic load shape of docs/14_wave_packing.md) and
    report per-template latency percentiles on top of the aggregate:
    the returned report's :meth:`LoadReport.per_template` groups
    completions by template name (and :meth:`LoadReport.per_tenant` by
    tenant id when templates carry tenants — the QoS fairness view).
    Occupancy/padding live in
    ``service.stats()`` (``batch_occupancy``, ``lane_occupancy``) —
    the bench ``serve_mixed`` arm reads both."""
    requests, names = mixed_requests(templates, n_requests)
    report = run_load(service, requests, **run_load_kwargs)
    report.template_names = names
    return report
