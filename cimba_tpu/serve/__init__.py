"""cimba_tpu.serve — the experiment-serving layer (docs/13_serving.md).

Many concurrent experiment requests multiplexed onto the wave-streamed
runner's already-warm compiled programs: a single device-owner
dispatcher thread packs requests of the same *compatibility class*
(docs/14_wave_packing.md — requests differing only in params, R, seed,
priority, horizon-within-bucket, chunk budget, or summary path still
pack, each lane carrying its own seed/horizon column) into shared
pad-and-masked waves and slices pooled results back per request,
behind admission control, deadlines, cancellation, and
retry-with-backoff.

    from cimba_tpu import serve
    with serve.Service(max_wave=1024) as svc:
        h = svc.submit(serve.Request(spec, params, 64, seed=1))
        result = h.result()          # a runner.experiment.StreamResult

Submodules: :mod:`~cimba_tpu.serve.cache` (the bounded shared program
cache), :mod:`~cimba_tpu.serve.store` (the persistent AOT program
store — ``CIMBA_PROGRAM_STORE`` hydrates a fresh process to
warm-serving without recompiling, docs/15_program_store.md),
:mod:`~cimba_tpu.serve.sched` (queue/deadline/retry policy),
:mod:`~cimba_tpu.serve.service` (the dispatcher),
:mod:`~cimba_tpu.serve.device` (the preemptive device scheduler —
concurrent waves per device, memory-aware admission,
checkpoint-evict-restore preemption, docs/24_device_scheduler.md),
:mod:`~cimba_tpu.serve.client` (synthetic load drivers).  The
multi-tenant QoS plane — weighted-fair lane shares, quotas/rate limits
with structured :class:`RetryAfter`, EDF deadlines at the refill
admission point — lives in :mod:`cimba_tpu.qos` (docs/27_qos.md) and
activates via ``Service(qos=True)`` / the ``CIMBA_QOS`` env knob.
"""

from cimba_tpu.serve.cache import ProgramCache, warm
from cimba_tpu.serve.store import (
    ProgramStore,
    StoreInvalidationWarning,
    UnstableStoreKey,
    default_store,
    maybe_enable_persistent_cache,
)
from cimba_tpu.serve.client import (
    LoadReport,
    RequestTemplate,
    mixed_requests,
    percentile,
    run_load,
    run_mixed_load,
)
from cimba_tpu.serve.sched import (
    AdmissionQueue,
    Backoff,
    Cancelled,
    DeadlineExceeded,
    MemoryBudgetExceeded,
    QueueFull,
    RetriesExhausted,
    RetryAfter,
    ServeError,
    ServiceClosed,
)
from cimba_tpu.serve.service import Request, ResultHandle, Service

__all__ = [
    "ProgramCache", "warm",
    "ProgramStore", "StoreInvalidationWarning", "UnstableStoreKey",
    "default_store", "maybe_enable_persistent_cache",
    "LoadReport", "RequestTemplate", "percentile",
    "run_load", "run_mixed_load", "mixed_requests",
    "AdmissionQueue", "Backoff",
    "ServeError", "QueueFull", "ServiceClosed", "Cancelled",
    "DeadlineExceeded", "RetriesExhausted", "MemoryBudgetExceeded",
    "RetryAfter",
    "Request", "ResultHandle", "Service",
]
