"""The persistent AOT program store — zero-cold-start serving.

The bounded in-memory :class:`~cimba_tpu.serve.cache.ProgramCache` dies
with the process: at production scale every rollout re-pays minutes of
XLA compile per (spec, settings) program point before the first request
is served.  This module makes the **compiler artifact** the unit of
caching (the "Compiler-First … Portable O(1) Caching for Inference"
frame, PAPERS.md): compiled executables are serialized against a frozen,
*value-based* program key and a fresh process hydrates to warm-serving
without ever invoking XLA.  Two mechanisms, layered
(docs/15_program_store.md):

(a) **JAX's persistent compilation cache** — :func:`maybe_enable_
    persistent_cache` wires ``jax_compilation_cache_dir`` to
    ``<store>/xla`` whenever ``CIMBA_PROGRAM_STORE`` is set, so *every*
    jit on the streaming/serving path (init/chunk/fold and anything
    else) transparently becomes a disk hit on recompile.  This
    mechanism keys on jax's own HLO fingerprint and needs no help from
    us; it saves the XLA compile but still re-pays tracing and jax's
    dispatch-path setup per program.

(b) **The explicit artifact layer** — :class:`ProgramStore` AOT-
    compiles the ``(init, chunk)`` program pair per wave shape
    (``jit.lower(...).compile()``), serializes the loaded executables
    (``jax.experimental.serialize_executable``), and records them in a
    manifest under :func:`store_key` — a sha256 over the spec's
    **stable fingerprint** (functions hashed by code + closure
    *values*, never ``id()`` — entries must survive a process
    boundary, unlike the in-memory key) plus every trace-time setting
    the program bakes in.  Hydration returns shim callables that
    dispatch stored shapes straight to the deserialized executable and
    fall back to an ordinary ``jax.jit`` (mechanism (a) softening the
    recompile) for shapes the store has never seen.

Invalidation is strict and LOUD — the same contract as the
dispatch-time key verification in ``serve/service.py``: a jax/jaxlib
version bump, backend/platform drift, manifest-format bump, checksum
mismatch, truncated pickle, or fingerprint drift each produce a counted
miss (and a :class:`StoreInvalidationWarning` where there is a body to
point at), **never a wrong program and never a crash** — every failure
path degrades to recompiling exactly what the cache would have compiled
anyway.  When an executable cannot be serialized at save time (e.g. a
backend whose PjRt client does not implement executable serialization),
the entry records a **downgrade**: mechanism (a) still covers that
program, and ``stats()["downgrades"]`` says so instead of crashing the
save.
"""

from __future__ import annotations

# cimba-check: persist-path  (CHK001: no id() may feed what this module
# writes to disk — store keys must be value-based)

import contextlib
import dataclasses
import hashlib
import json
import os
import pickle
import socket
import threading
import time
import types
import warnings
from typing import Any, Optional

#: environment knob: the store root directory.  Setting it makes
#: :func:`default_store` attach a :class:`ProgramStore` to every
#: :class:`~cimba_tpu.serve.cache.ProgramCache` lookup AND wires jax's
#: persistent compilation cache under ``<root>/xla``.
STORE_ENV = "CIMBA_PROGRAM_STORE"

#: minimum compile seconds for mechanism (a)'s disk entries (0 = cache
#: everything, the zero-cold-start deploy default).
XLA_MIN_S_ENV = "CIMBA_PROGRAM_STORE_XLA_MIN_S"

#: manifest format version: bump on any layout/semantic change — old
#: stores then invalidate loudly instead of deserializing garbage.
#: 2: PR 17 added per-program ``footprint_bytes`` (the device
#: scheduler's memory-aware admission reads it off hydrated programs
#: without re-lowering, docs/24_device_scheduler.md).  Per-program
#: ``program_size`` (docs/25_compile_wall.md) is additive-optional —
#: readers tolerate its absence, so it needed no bump.
FORMAT = 2

MANIFEST = "manifest.json"
ARTIFACT_DIR = "artifacts"
MANIFEST_LOCK = "manifest.lock"


class StoreInvalidationWarning(UserWarning):
    """A store entry was rejected (corrupt, truncated, or from a
    different jax/backend/format) and the program will be recompiled."""


class StaleStoreLockWarning(UserWarning):
    """A manifest lockfile outlived its holder (dead pid or past the
    staleness window) and was broken.  Loud by design: a stale lock
    means some writer died mid-update — the manifest it left behind is
    still the previous consistent one (writes are atomic), but whoever
    operates the store should know a save was lost."""


class UnstableStoreKey(Exception):
    """The spec's structure cannot be fingerprinted by value (e.g. a
    block closes over an object with no deterministic content digest),
    so it has no process-independent store identity.  The in-memory
    cache still works; the store records a downgrade."""


# -- the value-based fingerprint ---------------------------------------------
#
# The in-memory ``cache.spec_fingerprint`` keys function-valued
# structure by ``id()`` — correct within one process (entries pin their
# spec against id recycling) but meaningless across a process boundary.
# The store's fingerprint digests functions by VALUE: module, qualname,
# bytecode, recursively-resolved constants, defaults, and closure cell
# *contents*.  A spec rebuilt from the same source in a fresh process
# (or a ``dataclasses.replace`` twin) digests identically; a model
# whose code or closed-over values changed digests differently and
# misses — never a wrong program.


# cimba-check: content-path
def _stable_code(code: types.CodeType, seen: dict) -> tuple:
    consts = tuple(
        _stable_code(c, seen) if isinstance(c, types.CodeType)
        else _stable_obj(c, seen)
        for c in code.co_consts
    )
    return (
        "code", code.co_code, consts, code.co_names, code.co_varnames,
        code.co_freevars, code.co_argcount, code.co_kwonlyargcount,
        code.co_flags,
    )


# cimba-check: content-path
def _stable_callable(fn, seen: dict) -> tuple:
    import functools

    if isinstance(fn, functools.partial):
        kw = tuple(sorted((fn.keywords or {}).items()))
        return (
            "partial", _stable_callable(fn.func, seen),
            _stable_obj(tuple(fn.args), seen), _stable_obj(kw, seen),
        )
    if isinstance(fn, types.MethodType):
        # a bound method's behavior depends on its instance too
        return (
            "method", _stable_callable(fn.__func__, seen),
            _stable_obj(fn.__self__, seen),
        )
    if id(fn) in seen:  # cimba: noqa(CHK001) — in-process revisit key only
        # revisited callable (a closure cycle, or one function shared
        # by several slots): a back-reference to its first-visit
        # ordinal, NOT a bare marker — (f, g, f) and (f, g, g) must
        # digest differently or two different models could share a
        # store key and hydrate each other's programs.  Only the
        # ORDINAL is digested; the id() is a transient dict key that
        # never leaves this call (hence the CHK001 suppressions).
        return ("ref", seen[id(fn)])  # cimba: noqa(CHK001)
    seen[id(fn)] = len(seen)  # cimba: noqa(CHK001) — ordinal is the value
    code = getattr(fn, "__code__", None)
    if code is None:
        mod = getattr(fn, "__module__", None)
        qn = getattr(fn, "__qualname__", None) or getattr(
            fn, "__name__", None
        )
        if qn is None:
            raise UnstableStoreKey(
                f"callable {fn!r} has no code object and no qualified "
                "name — it cannot be fingerprinted by value"
            )
        return ("c", mod, qn)
    cells: tuple = ()
    if fn.__closure__:
        cells = tuple(
            _stable_obj(c.cell_contents, seen) for c in fn.__closure__
        )
    defaults = (
        None if fn.__defaults__ is None
        else _stable_obj(tuple(fn.__defaults__), seen)
    )
    return (
        "fn", fn.__module__, fn.__qualname__, _stable_code(code, seen),
        cells, defaults,
    )


# cimba-check: content-path
def _stable_obj(v, seen: dict) -> tuple:
    """A deterministic, process-independent digestable view of ``v``.
    Raises :class:`UnstableStoreKey` for anything whose repr would
    embed a memory address — a weak component would let two different
    models share a store slot, which is the one failure mode the store
    must never have."""
    import numpy as np

    if v is None or isinstance(v, (bool, int, float, str, bytes)):
        return ("p", repr(v))
    if isinstance(v, np.ndarray):
        return ("nd", str(v.dtype), v.shape, v.tobytes())
    if isinstance(v, np.generic):
        return ("ns", str(v.dtype), v.tobytes())
    if isinstance(v, np.dtype):
        return ("dt", str(v))
    if isinstance(v, (list, tuple)):
        return (
            "seq", type(v).__name__,
            tuple(_stable_obj(x, seen) for x in v),
        )
    if isinstance(v, (set, frozenset)):
        return (
            "set", tuple(sorted(_stable_obj(x, seen) for x in v)),
        )
    if isinstance(v, dict):
        items = sorted(
            ((_stable_obj(k, seen), _stable_obj(x, seen))
             for k, x in v.items())
        )
        return ("map", tuple(items))
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        return (
            "dc", type(v).__module__, type(v).__qualname__,
            tuple(
                (f.name, _stable_obj(getattr(v, f.name), seen))
                for f in dataclasses.fields(v)
            ),
        )
    try:
        import jax
    except ImportError:
        jax = None  # jax-less tooling digests everything else the same way
    if jax is not None and isinstance(v, jax.Array):
        try:
            a = np.asarray(v)
        except Exception as e:
            # a donated/deleted buffer or leaked tracer: structured,
            # degradable failure — callers catch UnstableStoreKey and
            # record a downgrade (the invalidation-ladder contract),
            # never a raw RuntimeError out of the serving layer
            raise UnstableStoreKey(
                f"jax array in spec structure failed host conversion "
                f"({type(e).__name__}: {e}) — it has no stable value "
                "digest"
            ) from e
        return ("jx", str(a.dtype), a.shape, a.tobytes())
    if callable(v):
        return _stable_callable(v, seen)
    raise UnstableStoreKey(
        f"{type(v).__module__}.{type(v).__qualname__} has no "
        "deterministic value digest — the spec closing over it cannot "
        "be stored persistently"
    )


# cimba-check: content-path
def stable_spec_fingerprint(spec) -> tuple:
    """The VALUE-based structural identity of a ModelSpec — the
    persistent twin of ``cache.spec_fingerprint`` with every ``id()``
    replaced by a content digest, so a spec reconstructed in a fresh
    process (or a ``dataclasses.replace`` twin) maps to the same store
    entry.  Raises :class:`UnstableStoreKey` when any function-valued
    structure resists value fingerprinting."""
    import numpy as np

    cached = getattr(spec, "_cimba_stable_fingerprint", None)
    if cached is not None:
        return cached

    seen: dict = {}  # id -> first-visit ordinal (back-references)
    fp = (
        spec.name,
        tuple(_stable_callable(b, seen) for b in spec.blocks),
        np.asarray(spec.proc_entry).tobytes(),
        np.asarray(spec.proc_prio).tobytes(),
        np.asarray(spec.proc_start).tobytes(),
        tuple(spec.proc_names),
        tuple(_stable_obj(q, seen) for q in spec.queues),
        tuple(_stable_obj(r, seen) for r in spec.resources),
        tuple(_stable_obj(p, seen) for p in spec.pools),
        tuple(_stable_obj(b, seen) for b in spec.buffers),
        tuple(_stable_obj(q, seen) for q in spec.pqueues),
        tuple(_stable_obj(c, seen) for c in spec.conditions),
        spec.n_guards, spec.guard_cap, spec.event_cap,
        spec.queue_cap_max, spec.pqueue_cap_max,
        spec.n_flocals, spec.n_ilocals, spec.max_chain,
        None if spec.user_init is None
        else _stable_callable(spec.user_init, seen),
        tuple(_stable_callable(h, seen) for h in spec.user_handlers),
        tuple(spec.boundary_pcs),
    )
    try:
        object.__setattr__(spec, "_cimba_stable_fingerprint", fp)
    except (AttributeError, TypeError):
        pass  # slotted/frozen spec: recompute per call
    return fp


# cimba-check: content-path
def callable_digest(fn) -> str:
    """The stable content digest of one callable (sha256 hex) — how
    fold artifacts are keyed to their ``summary_path`` across process
    boundaries.  Raises :class:`UnstableStoreKey` when the callable
    resists value fingerprinting."""
    return hashlib.sha256(
        repr(_stable_callable(fn, {})).encode("utf-8")
    ).hexdigest()


# cimba-check: content-path
def _mesh_descriptor(mesh) -> Optional[tuple]:
    if mesh is None:
        return None
    kinds = sorted(
        {
            f"{d.platform}:{getattr(d, 'device_kind', '?')}"
            for d in mesh.devices.flat
        }
    )
    return (
        "mesh", tuple(mesh.axis_names), tuple(mesh.devices.shape),
        tuple(kinds),
    )


# cimba-check: content-path
def store_key(
    spec, with_metrics: bool, *, mesh, pack, chunk_steps: int,
) -> str:
    """The persistent program key: sha256 hex over the stable spec
    fingerprint plus every trace-time setting a compiled program bakes
    in — the value-based image of ``cache.program_key`` (same field
    set, trace-time globals resolved NOW), so "same store key" implies
    "same program" exactly as it does in memory.  Raises
    :class:`UnstableStoreKey` when the spec has no value identity."""
    from cimba_tpu import config as _config
    from cimba_tpu.obs import trace as _trace

    key = (
        FORMAT,
        stable_spec_fingerprint(spec),
        _config.active_profile(),
        bool(with_metrics),
        bool(pack if pack is not None else _config.xla_pack_enabled()),
        _trace.enabled(),
        _config.eventset_hier_enabled(),
        _config.eventset_block(),
        _mesh_descriptor(mesh),
        int(chunk_steps),
    )
    return hashlib.sha256(repr(key).encode("utf-8")).hexdigest()


def _environment() -> dict:
    """The strict-match environment guard recorded per entry: an
    executable is an opaque backend artifact, so ANY drift here
    invalidates rather than risking a misload."""
    import jax
    import jaxlib

    dev = jax.devices()[0]
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": jax.default_backend(),
        "device_kind": getattr(dev, "device_kind", "?"),
        "n_devices": jax.device_count(),
        "x64": bool(jax.config.jax_enable_x64),
    }


# cimba-check: content-path
def _args_sig_digest(args) -> str:
    """The shape signature of one compiled specialization: pytree
    structure plus per-leaf (dtype, shape, weak_type).  The hydration
    shim dispatches to a stored executable only on an EXACT match —
    anything else falls back to jit, never to a near-miss program."""
    import jax
    from jax.api_util import shaped_abstractify

    leaves, treedef = jax.tree_util.tree_flatten(args)
    sig = (
        str(treedef),
        tuple(
            (str(a.dtype), tuple(a.shape), bool(a.weak_type))
            for a in map(shaped_abstractify, leaves)
        ),
    )
    return hashlib.sha256(repr(sig).encode("utf-8")).hexdigest()


# -- mechanism (a): jax's persistent compilation cache ------------------------

_XLA_WIRED: Optional[str] = None


def maybe_enable_persistent_cache(root: Optional[str] = None):
    """Wire jax's persistent compilation cache under ``<root>/xla``
    (mechanism (a)).  ``root=None`` reads ``CIMBA_PROGRAM_STORE`` and
    no-ops when unset — safe to call on every streaming/serving entry
    point.  Idempotent; re-wires if the root changes.  Returns the
    cache dir (or None)."""
    global _XLA_WIRED
    import jax

    from cimba_tpu import config as _config

    if root is None:
        root = _config.env_raw(STORE_ENV).strip() or None
        if root is None:
            return None
    xdir = os.path.join(os.path.abspath(root), "xla")
    if _XLA_WIRED == xdir:
        return xdir
    os.makedirs(xdir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", xdir)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs",
        float(_config.env_raw(XLA_MIN_S_ENV)),
    )
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    _XLA_WIRED = xdir
    return xdir


# -- the store ----------------------------------------------------------------

_STORES: dict = {}


def get_store(root: str) -> "ProgramStore":
    """The process-wide :class:`ProgramStore` for ``root`` — one
    instance per (absolute) root, so hit/miss counters aggregate across
    every cache and ``serve.warm`` call in the process, which is what
    ``Service.stats()`` reports."""
    key = os.path.abspath(root)
    st = _STORES.get(key)
    if st is None:
        st = _STORES[key] = ProgramStore(key)
    return st


def default_store() -> Optional["ProgramStore"]:
    """The process-wide store named by ``CIMBA_PROGRAM_STORE`` (None
    when unset)."""
    from cimba_tpu import config as _config

    root = _config.env_raw(STORE_ENV).strip()
    if not root:
        return None
    return get_store(root)


class _LazyArtifact:
    """One checksum-verified artifact blob whose
    ``deserialize_and_load`` is deferred until first use (and memoized).
    Hydration reads+verifies every blob eagerly — corruption is still
    detected at hydrate time — but a lookup that only ever dispatches
    one wave shape never pays deserialization for the others.
    ``serve.warm(manifest=...)`` resolves eagerly on the calling
    thread (deserialization measured ~4.6x slower on the dispatcher
    thread, BENCH_NOTES round 8)."""

    __slots__ = ("_blob", "_loaded", "file")

    def __init__(self, blob: bytes, file: str):
        self._blob = blob
        self._loaded = None
        self.file = file

    def resolve(self):
        if self._loaded is None:
            from jax.experimental import serialize_executable as _se

            self._loaded = _se.deserialize_and_load(
                *pickle.loads(self._blob)
            )
            self._blob = None
        return self._loaded


class HydratedPrograms(tuple):
    """What :meth:`ProgramStore.hydrate` returns: ``(init, chunk)``
    shims plus the loaded fold executables keyed by
    ``(summary_path digest, shape digest)`` — indexable like the old
    2-tuple (``hyd[0]``/``hyd[1]``) for ``get_programs``."""

    __slots__ = ()

    def __new__(cls, init, chunk, folds):
        return tuple.__new__(cls, (init, chunk, folds))

    @property
    def init(self):
        return self[0]

    @property
    def chunk(self):
        return self[1]

    @property
    def folds(self) -> dict:
        return self[2]


def hydrated_fold(jit_fn, table: dict, store: "ProgramStore"):
    """Wrap a jitted fold program with a store-artifact dispatch table
    (the ``serve.warm(manifest=...)`` fold path)."""
    return _HydratedProgram(jit_fn, table, store, "fold")


class _HydratedProgram:
    """A callable standing where a jitted ``init``/``chunk`` program
    stands: stored shapes dispatch straight to the deserialized
    executable (zero compiles); unseen shapes — and abstract tracers,
    e.g. the preflight's ``eval_shape`` — fall back to the wrapped
    ``jax.jit`` program, which mechanism (a) softens to a disk hit."""

    __slots__ = ("_jit", "_table", "_store", "_role", "_fallback_seen",
                 "_footprints")

    def __init__(self, jit_fn, table: dict, store: "ProgramStore",
                 role: str, footprints: Optional[dict] = None):
        self._jit = jit_fn
        self._table = table
        self._store = store
        self._role = role
        self._fallback_seen: set = set()
        # per-shape measured device footprint (bytes), from the
        # manifest's ``footprint_bytes`` — the memory-aware admission
        # input that needs no re-lowering (docs/24_device_scheduler.md)
        self._footprints: dict = footprints or {}

    def __call__(self, *args):
        import jax

        leaves = jax.tree_util.tree_leaves(args)
        if any(isinstance(x, jax.core.Tracer) for x in leaves):
            return self._jit(*args)
        sig = _args_sig_digest(args)
        art = self._table.get(sig)
        if art is None:
            if sig not in self._fallback_seen:
                self._fallback_seen.add(sig)
                self._store._count("fallback_shapes")
            return self._jit(*args)
        try:
            fn = art.resolve()
        except Exception as e:
            # a blob that checksummed but won't deserialize: reject
            # loudly, drop it, and recompile — never serve a maybe
            self._table.pop(sig, None)
            warnings.warn(
                f"program store artifact {art.file} failed to "
                f"deserialize ({type(e).__name__}: {e}); recompiling",
                StoreInvalidationWarning,
            )
            self._store._count("corrupt")
            return self._jit(*args)
        self._store._count("artifact_dispatches")
        return fn(*args)

    def resolve_all(self) -> None:
        """Eagerly deserialize every stored shape (the
        ``serve.warm(manifest=...)`` main-thread path)."""
        for art in self._table.values():
            art.resolve()

    def lower(self, *args, **kwargs):
        return self._jit.lower(*args, **kwargs)

    def footprint_for(self, *args) -> Optional[int]:
        """The store-measured device footprint (bytes) of this
        program at the given arg shapes, or None when the manifest
        carries none for that shape (``cache.wave_footprint_bytes``
        then falls through to its next rung)."""
        return self._footprints.get(_args_sig_digest(args))


class ProgramStore:
    """A directory of serialized compiled programs keyed by
    :func:`store_key`, with a JSON manifest and strict invalidation.

    Layout::

        <root>/manifest.json     entries: key -> {env, programs, meta}
                                 tuned:   key -> {env, schedule, meta}
        <root>/artifacts/*.bin   pickled (payload, in_tree, out_tree)
        <root>/xla/              mechanism (a)'s compilation cache

    The ``tuned`` section is the schedule-autotuner registry
    (docs/21_autotune.md, written/read via
    :mod:`cimba_tpu.tune.registry`): searched dispatch-schedule
    winners keyed by (value-based spec fingerprint, backend, device
    kind, workload bucket), invalidated by environment drift exactly
    like artifacts.

    Writes are crash-atomic (mkstemp + fsync + ``os.replace`` — the
    checkpoint discipline): a killed save leaves the previous manifest
    intact, and a torn artifact fails its checksum on load instead of
    deserializing garbage.  Manifest UPDATES additionally serialize
    across processes through an ``O_EXCL`` lockfile
    (:meth:`_manifest_lock`): two processes warming the same store
    merge their entries instead of losing one side's, and a stale lock
    (dead writer) is broken with a loud
    :class:`StaleStoreLockWarning`."""

    # cimba-check: must-hold(_lock) _stats

    def __init__(self, root: str, *, enable_xla_cache: bool = True,
                 lock_timeout_s: float = 60.0,
                 lock_stale_s: float = 30.0):
        self.root = os.path.abspath(root)
        self._lock_timeout_s = float(lock_timeout_s)
        self._lock_stale_s = float(lock_stale_s)
        os.makedirs(os.path.join(self.root, ARTIFACT_DIR), exist_ok=True)
        if enable_xla_cache:
            maybe_enable_persistent_cache(self.root)
        # RLock: _read_manifest counts corrupt/invalidated manifests
        # via _count while hydrate/save/covered already hold the lock
        self._lock = threading.RLock()
        self._stats = {
            "saves": 0,
            "hits": 0,
            "misses": 0,
            "invalidated": 0,
            "corrupt": 0,
            "downgrades": 0,
            "fallback_shapes": 0,
            "artifact_dispatches": 0,
            # the tuned-schedule registry (docs/21_autotune.md): the
            # manifest's "tuned" section rides the same lock + atomic
            # write + env invalidation ladder as the artifacts
            "tuned_saves": 0,
            "tuned_hits": 0,
            "tuned_misses": 0,
            "tuned_invalidated": 0,
        }

    # -- observability -------------------------------------------------------

    def _count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._stats[name] += n

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._stats)
        out["root"] = self.root
        # boolean health flags for /healthz (docs/17_telemetry.md): any
        # of these true means the store degraded at least once this
        # process — serving still works (every rung degrades to
        # recompile), but an operator should look before trusting
        # cold-start numbers
        out["flags"] = {
            "corruption": out["corrupt"] > 0,
            "invalidated": out["invalidated"] > 0,
            "downgraded": out["downgrades"] > 0,
        }
        return out

    # -- manifest ------------------------------------------------------------

    def _manifest_path(self) -> str:
        return os.path.join(self.root, MANIFEST)

    def _manifest_lock_path(self) -> str:
        return os.path.join(self.root, MANIFEST_LOCK)

    @staticmethod
    def _pid_alive(pid: int) -> bool:
        try:
            os.kill(int(pid), 0)
        except (ProcessLookupError, ValueError):
            return False
        except PermissionError:
            return True  # alive, just not ours
        return True

    @contextlib.contextmanager
    def _manifest_lock(self):
        """Inter-PROCESS mutual exclusion around manifest
        read-merge-write (the in-process ``_lock`` covers threads; this
        covers two processes warming the same store — two
        ``warm_store`` runs, or a fleet of slices saving autotuned
        programs — whose unlocked read-modify-write would silently lose
        one side's entries).

        Mechanics: an ``O_CREAT | O_EXCL`` lockfile beside the manifest
        holding ``{pid, host, t}``; losers poll.  A lock held by a
        provably-DEAD pid on this host — or older than
        ``lock_stale_s`` when the holder's liveness is unknowable
        (foreign host, unreadable body) — is broken by atomic rename
        with a LOUD :class:`StaleStoreLockWarning` naming the holder
        (the atomic manifest write guarantees what's on disk is the
        previous consistent generation).  A provably-ALIVE same-host
        holder is never broken, however old: waiting past
        ``lock_timeout_s`` raises ``TimeoutError`` — better a loud
        failed save than two writers in the manifest."""
        path = self._manifest_lock_path()
        me = {
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "t": time.time(),
        }
        deadline = time.monotonic() + self._lock_timeout_s
        while True:
            try:
                fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                try:
                    age = time.time() - os.stat(path).st_mtime
                except FileNotFoundError:
                    continue     # released between open and stat: retry
                except OSError:
                    time.sleep(0.02)
                    continue
                holder: dict = {}
                try:
                    with open(path, "r") as f:
                        holder = json.load(f)
                except (OSError, json.JSONDecodeError):
                    # empty/torn body: either a writer between O_EXCL
                    # and write (age ~0 — wait) or one that CRASHED in
                    # that window (age grows).  Liveness is unknowable,
                    # so fall through with an empty holder and let the
                    # age/timeout ladder decide — a blind retry here
                    # would spin forever on a permanently-empty lock.
                    holder = {}
                same_host = holder.get("host") == me["host"]
                has_pid = holder.get("pid") is not None
                dead = (
                    same_host and has_pid
                    and not self._pid_alive(holder["pid"])
                )
                # a holder whose liveness is PROVABLE (same host, pid
                # answers kill-0) is never age-broken: a slow-but-alive
                # writer past lock_stale_s must hit the Timeout path
                # below, not have its lock stolen mid-write (the
                # double-writer corruption this lock exists to close).
                # Age-breaking applies only where liveness is
                # unknowable: foreign hosts and unreadable pids.
                alive_here = same_host and has_pid and not dead
                if dead or (
                    age > self._lock_stale_s and not alive_here
                ):
                    # break by ATOMIC rename, not unlink: two waiters
                    # may both judge the same lock stale, and a bare
                    # unlink from the loser could delete the winner's
                    # freshly-acquired lock — exactly the double-writer
                    # hole this lockfile exists to close.  rename
                    # succeeds for exactly one breaker; everyone else
                    # gets FileNotFoundError and just re-contends.
                    broken = f"{path}.broken.{os.getpid()}"
                    try:
                        os.rename(path, broken)
                    except OSError:
                        continue  # someone else broke/released it first
                    warnings.warn(
                        f"broke stale program-store manifest lock "
                        f"{path} (holder pid={holder.get('pid')} "
                        f"host={holder.get('host')!r}, age {age:.1f}s, "
                        f"{'dead' if dead else 'past staleness window'})"
                        " — a writer died mid-update; its save was lost",
                        StaleStoreLockWarning,
                    )
                    try:
                        os.unlink(broken)
                    except OSError:
                        pass
                    continue
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"program-store manifest lock {path} held by "
                        f"pid={holder.get('pid')} "
                        f"host={holder.get('host')!r} for {age:.1f}s — "
                        f"gave up after {self._lock_timeout_s:.0f}s"
                    )
                time.sleep(0.02)
                continue
            try:
                os.write(fd, json.dumps(me).encode("utf-8"))
            finally:
                os.close(fd)
            break
        try:
            yield
        finally:
            # release only what is still OURS: if another process
            # judged us stale and stole the lock (we ran past
            # lock_stale_s), the file now holds THEIR identity and a
            # blind unlink would hand the manifest to a third writer
            try:
                with open(path, "r") as f:
                    cur = json.load(f)
                if (
                    cur.get("pid") == me["pid"]
                    and cur.get("host") == me["host"]
                ):
                    os.unlink(path)
            except (OSError, json.JSONDecodeError):
                pass  # already broken/released — nothing of ours left

    def _update_manifest(self, mutate) -> dict:
        """One atomic cross-process read-merge-write of the manifest:
        ``mutate(manifest)`` runs with the inter-process lockfile held
        (which serializes THREADS too — an O_EXCL create fails the same
        way for a sibling thread as for a foreign process), then the
        result lands via the crash-atomic write.  Deliberately NOT
        under ``self._lock``: the file-lock wait can last seconds
        (another process saving), and holding the thread lock across
        it would stall ``stats()`` — and with it the telemetry scrape
        behind ``/healthz`` — long enough to fake a dead slice.
        Returns the written manifest."""
        with self._manifest_lock():
            manifest = self._read_manifest()
            mutate(manifest)
            self._write_manifest(manifest)
        return manifest

    def _read_manifest(self) -> dict:
        try:
            with open(self._manifest_path(), "r") as f:
                m = json.load(f)
        except FileNotFoundError:
            return {"format": FORMAT, "entries": {}}
        except (json.JSONDecodeError, OSError) as e:
            warnings.warn(
                f"program store manifest at {self._manifest_path()} is "
                f"unreadable ({e!r}); treating the store as empty",
                StoreInvalidationWarning,
            )
            self._count("corrupt")
            return {"format": FORMAT, "entries": {}}
        if m.get("format") != FORMAT:
            warnings.warn(
                f"program store manifest format {m.get('format')!r} != "
                f"{FORMAT} — the whole store is invalidated (rebuild "
                "with tools/warm_store.py)",
                StoreInvalidationWarning,
            )
            self._count("invalidated")
            return {"format": FORMAT, "entries": {}}
        return m

    def _atomic_write(self, path: str, data: bytes) -> None:
        import tempfile

        d = os.path.dirname(path)
        fd, tmp = tempfile.mkstemp(
            dir=d, prefix=os.path.basename(path) + ".", suffix=".tmp"
        )
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def _write_manifest(self, manifest: dict) -> None:
        self._atomic_write(
            self._manifest_path(),
            json.dumps(manifest, indent=1, sort_keys=True).encode(),
        )

    # -- save ----------------------------------------------------------------

    def save_programs(
        self,
        spec,
        params: Any,
        n_replications: int,
        *,
        wave_sizes,
        mesh=None,
        pack=None,
        chunk_steps: int = 1024,
        with_metrics: Optional[bool] = None,
        horizon_modes=("none", "column"),
        summary_paths=None,
        seed: int = 0,
    ) -> dict:
        """AOT-compile and serialize the ``(init, chunk)`` pair for
        every ``wave_sizes`` × ``horizon_modes`` point of this (spec,
        settings) program key, exactly as the stream runner / service
        would dispatch them (``horizon_modes``: ``"none"`` = the
        run-to-completion pytree without the ``t_stop`` leaf, the
        stream default; ``"column"`` = the per-lane horizon column the
        serving layer's padded / finite-horizon waves carry).
        ``summary_paths`` (default: the runner's
        ``default_summary_path``) additionally compiles + serializes
        the wave-FOLD program per path × shape, keyed by the path's
        :func:`callable_digest` — so ``serve.warm(manifest=...)``
        reaches first-request readiness with zero executions; pass
        ``()`` to skip folds.  Returns a report dict with per-program
        compile seconds and artifact bytes.  A program whose
        executable cannot be serialized (or a fold whose path is
        unstable / fails to trace on this model) records a
        **downgrade** (mechanism (a) still covers it) instead of
        raising; only an unstable spec fingerprint raises
        (:class:`UnstableStoreKey` — there is no key to save under)."""
        import jax
        from jax.experimental import serialize_executable as _se

        from cimba_tpu.obs import metrics as _metrics
        from cimba_tpu.runner import experiment as ex

        if with_metrics is None:
            with_metrics = _metrics.enabled()
        key = store_key(
            spec, with_metrics, mesh=mesh, pack=pack,
            chunk_steps=chunk_steps,
        )
        init_j = ex._init_program(spec, mesh)
        chunk_j = ex._chunk_program(spec, None, pack, chunk_steps, mesh)

        programs = []
        downgrades = []
        report = {
            "key": key, "model": spec.name, "programs": [],
            "downgrades": downgrades,
        }

        def psize(fn, fn_args, lowered, lower_s):
            """Program-size record for one saved program
            (docs/25_compile_wall.md): the trace-only obs probe plus
            the HLO text bytes off the ALREADY-lowered module (no
            re-lower).  Sits next to ``footprint_bytes`` in the
            manifest — device memory and program text are the two
            sizes that gate a deploy.  Best-effort: a spec the probe
            can't re-trace degrades to None, never a failed save."""
            try:
                from cimba_tpu.obs import program_size as _psz

                d = _psz.measure(fn, *fn_args, lower=False).to_dict()
                d["lower_s"] = round(lower_s, 4)
                try:
                    d["hlo_bytes"] = len(lowered.as_text().encode())
                except Exception:
                    d["hlo_bytes"] = None
                return d
            except Exception:
                return None

        def emit(role, args_sig_args, compiled, compile_s, path=None,
                 size=None):
            sig = _args_sig_digest(args_sig_args)
            try:
                payload = _se.serialize(compiled)
                blob = pickle.dumps(payload, protocol=4)
            except Exception as e:
                self._count("downgrades")
                downgrades.append(
                    {"role": role, "shape": sig,
                     "reason": f"{type(e).__name__}: {e}"}
                )
                return
            frag = f"{path[:8]}-" if path else ""
            fname = f"{key[:16]}-{role}-{frag}{sig[:16]}.bin"
            self._atomic_write(
                os.path.join(self.root, ARTIFACT_DIR, fname), blob
            )
            rec = {
                "role": role,
                "shape": sig,
                "file": fname,
                "sha256": hashlib.sha256(blob).hexdigest(),
                "bytes": len(blob),
                "compile_s": compile_s,
            }
            # measured device footprint, where the backend implements
            # memory_analysis() — hydrated admission reads it instead
            # of re-lowering (docs/24_device_scheduler.md); absent on
            # backends without the API (estimate rung covers them)
            try:
                from cimba_tpu.serve import cache as _pcache

                fp = _pcache._memory_analysis_bytes(
                    compiled.memory_analysis()
                )
            except Exception:
                fp = None
            if fp is not None:
                rec["footprint_bytes"] = int(fp)
            if size is not None:
                rec["program_size"] = size
            if path is not None:
                rec["path"] = path
            programs.append(rec)
            report["programs"].append(dict(rec))

        if summary_paths is None:
            summary_paths = (ex.default_summary_path,)
        folds = []
        for sp in summary_paths:
            try:
                pdig = callable_digest(sp)
            except UnstableStoreKey as e:
                self._count("downgrades")
                downgrades.append(
                    {"role": "fold", "shape": "?",
                     "reason": f"unstable summary_path: {e}"}
                )
                continue
            folds.append((sp, pdig))

        for n in wave_sizes:
            n = int(n)
            reps = jax.numpy.arange(n)
            seeds = ex._seed_column(seed, n)
            pw = ex._slice_params(params, int(n_replications), 0, n)
            for hz in horizon_modes:
                t_stops = (
                    None if hz == "none" else ex._horizon_column(None, n)
                )
                args = (reps, seeds, t_stops, pw)
                t0 = time.monotonic()
                init_low = init_j.lower(*args)
                t_init_low = time.monotonic() - t0
                init_c = init_low.compile()
                t_init = time.monotonic() - t0
                emit("init", args, init_c, t_init,
                     size=psize(init_j, args, init_low, t_init_low))
                sims_aval = jax.eval_shape(init_j, *args)
                t0 = time.monotonic()
                chunk_low = chunk_j.lower(sims_aval)
                t_chunk_low = time.monotonic() - t0
                chunk_c = chunk_low.compile()
                t_chunk = time.monotonic() - t0
                emit("chunk", (sims_aval,), chunk_c, t_chunk,
                     size=psize(chunk_j, (sims_aval,), chunk_low,
                                t_chunk_low))
                for sp, pdig in folds:
                    from cimba_tpu.serve import cache as _pcache

                    acc = _pcache.stream_acc(spec, with_metrics)
                    fold_j = _pcache._fold_program(with_metrics, sp)
                    try:
                        t0 = time.monotonic()
                        fold_low = fold_j.lower(acc, sims_aval)
                        t_fold_low = time.monotonic() - t0
                        fold_c = fold_low.compile()
                        t_fold = time.monotonic() - t0
                    except Exception as e:
                        # a path that doesn't exist on this model's Sim
                        # (or doesn't trace) — record, don't crash
                        self._count("downgrades")
                        downgrades.append(
                            {"role": "fold", "shape": "?",
                             "reason": f"{type(e).__name__}: {e}"}
                        )
                        continue
                    emit(
                        "fold", (acc, sims_aval), fold_c, t_fold,
                        path=pdig,
                        size=psize(fold_j, (acc, sims_aval), fold_low,
                                   t_fold_low),
                    )

        # the merge key carries the summary-path digest too: fold
        # records for different paths share arg shapes, and a
        # shape+role key would silently keep only the last path's
        def mkey(p):
            return (p["role"], p["shape"], p.get("path"))

        def merge_entry(manifest):
            # runs under BOTH the thread lock and the inter-process
            # manifest lockfile: a second process saving a different
            # program key concurrently merges instead of clobbering
            # (the two-subprocess race test in tests/test_store.py)
            entry = manifest["entries"].get(key, {})
            merged = {mkey(p): p for p in entry.get("programs", [])}
            for p in programs:
                merged[mkey(p)] = p
            manifest["entries"][key] = {
                "model": spec.name,
                "env": _environment(),
                "created": time.time(),
                "meta": {
                    "chunk_steps": int(chunk_steps),
                    "with_metrics": bool(with_metrics),
                    "wave_sizes": [int(n) for n in wave_sizes],
                    "horizon_modes": list(horizon_modes),
                },
                "programs": sorted(
                    merged.values(),
                    key=lambda p: (p["role"], p["shape"],
                                   p.get("path") or ""),
                ),
                "downgrades": downgrades,
            }

        self._update_manifest(merge_entry)
        with self._lock:
            self._stats["saves"] += 1
        return report

    # -- hydrate -------------------------------------------------------------

    def hydrate(
        self,
        spec,
        *,
        mesh=None,
        pack=None,
        chunk_steps: int = 1024,
        with_metrics: bool = False,
    ):
        """Second-chance lookup for ``cache.get_programs``: return a
        hydrated ``(init, chunk)`` pair for this program key, or None
        on any miss.  The invalidation ladder — key absent, jax/jaxlib
        version drift, backend/platform drift, checksum mismatch,
        truncated/corrupt artifact, deserialization failure — each
        step degrades to a counted miss (with a
        :class:`StoreInvalidationWarning` where a rejected body
        exists), NEVER to a mismatched program: one corrupt artifact
        rejects the whole entry so init and chunk can never come from
        different generations.  Artifact BYTES are read and
        checksum-verified here; deserialization is lazy per dispatched
        shape (see :class:`_LazyArtifact`)."""
        from cimba_tpu.runner import experiment as ex

        try:
            key = store_key(
                spec, with_metrics, mesh=mesh, pack=pack,
                chunk_steps=chunk_steps,
            )
        except UnstableStoreKey:
            self._count("misses")
            return None
        with self._lock:
            manifest = self._read_manifest()
        entry = manifest["entries"].get(key)
        if entry is None:
            self._count("misses")
            return None
        env = _environment()
        if entry.get("env") != env:
            drift = {
                k: (entry.get("env", {}).get(k), env[k])
                for k in env
                if entry.get("env", {}).get(k) != env[k]
            }
            warnings.warn(
                f"program store entry {key[:16]} was built in a "
                f"different environment ({drift}); recompiling instead "
                "of loading a foreign executable",
                StoreInvalidationWarning,
            )
            self._count("invalidated")
            return None
        tables: dict = {"init": {}, "chunk": {}}
        footprints: dict = {"init": {}, "chunk": {}}
        folds: dict = {}
        for rec in entry.get("programs", []):
            path = os.path.join(self.root, ARTIFACT_DIR, rec["file"])
            try:
                with open(path, "rb") as f:
                    blob = f.read()
                if hashlib.sha256(blob).hexdigest() != rec["sha256"]:
                    raise ValueError("artifact checksum mismatch")
                # checksum verified NOW; deserialization is deferred to
                # first dispatch of the shape (or warm's resolve_all)
                loaded = _LazyArtifact(blob, rec["file"])
            except Exception as e:
                warnings.warn(
                    f"program store artifact {rec['file']} is "
                    f"corrupt/unloadable ({type(e).__name__}: {e}); "
                    "rejecting the whole entry and recompiling",
                    StoreInvalidationWarning,
                )
                self._count("corrupt")
                return None
            if rec["role"] == "fold":
                folds[(rec.get("path"), rec["shape"])] = loaded
            else:
                tables.setdefault(rec["role"], {})[rec["shape"]] = loaded
                if rec.get("footprint_bytes") is not None:
                    footprints.setdefault(rec["role"], {})[
                        rec["shape"]
                    ] = int(rec["footprint_bytes"])
        if not tables["init"] and not tables["chunk"]:
            self._count("misses")
            return None
        self._count("hits")
        init_j = ex._init_program(spec, mesh)
        chunk_j = ex._chunk_program(spec, None, pack, chunk_steps, mesh)
        return HydratedPrograms(
            _HydratedProgram(init_j, tables["init"], self, "init",
                             footprints["init"]),
            _HydratedProgram(chunk_j, tables["chunk"], self, "chunk",
                             footprints["chunk"]),
            folds,
        )

    def covered(
        self, spec, *, mesh=None, pack=None, chunk_steps: int = 1024,
        with_metrics: bool = False,
    ) -> bool:
        """True when a valid-looking manifest entry exists for this
        program key (environment checked; artifact bytes are only
        verified at :meth:`hydrate` time)."""
        try:
            key = store_key(
                spec, with_metrics, mesh=mesh, pack=pack,
                chunk_steps=chunk_steps,
            )
        except UnstableStoreKey:
            return False
        with self._lock:
            entry = self._read_manifest()["entries"].get(key)
        return bool(entry) and entry.get("env") == _environment()
