"""The experiment service: many clients multiplexed onto shared waves.

PR 3 built the primitive a service needs — one compiled chunk program
that waves of lanes stream through (`runner.run_experiment_stream`) —
but every entry point was a blocking, single-caller function.  This
module multiplexes many concurrent experiment requests onto those
already-warm programs:

* **One device-owner dispatcher thread** (cimba's one-event-loop-per-
  worker discipline, transposed: the DEVICE is the scarce resource, so
  exactly one thread builds batches and dispatches programs; client
  threads only enqueue and wait on futures).
* **Compatibility-class packing** (docs/14_wave_packing.md): queued
  requests of the same *compatibility class* — the spec's structural
  fingerprint, dtype profile, metrics/trace/eventset flags, resolved
  pack arm, mesh (`serve.cache.program_class_key`), the params tree
  signature, and the horizon bucket — are packed into ONE wave of the
  shared compiled chunk program, and the pooled results are sliced
  back per request.  Seed, parameter VALUES, R, priority, horizon
  value, and chunk budget are per-lane data (or trajectory-invariant),
  so requests differing only in them pack unconditionally: each lane
  carries its own seed and `t_stop` column, a short-horizon lane goes
  dead early inside a longer wave (exact truncation via the chunked
  driver's `any_live` early-exit), and partially-filled waves are
  padded to a quantized shape with dead masked lanes (`t_stop=-inf`)
  that are bitwise-inert for the live lanes.  The class is
  definitionally a prefix of the compiled-program key, so packing can
  never mix trajectories that belong to different programs.
* **Bitwise request isolation**: lanes are independent under `vmap`
  (the masking/donation contract of docs/12), so a request packed with
  strangers produces results bitwise equal to the direct
  `run_experiment_stream` call with the same `wave_size` — the slot
  partition `n = min(wave_size, R - lo)` reproduces the direct call's
  wave partition, each slot's slice folds through the SAME jitted fold
  program, and the accumulator starts from the same zeros
  (`tests/test_serve.py` pins this with concurrent mixed clients).

Around the dispatcher: admission control with a bounded queue and
blocking backpressure, per-request deadlines and cancellation, and
retry-with-exponential-backoff on dispatch failure that never stalls
the queue (failed requests back off in a delay heap while the
dispatcher keeps serving; see `serve.sched`).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Optional

from cimba_tpu.serve import cache as _pcache
from cimba_tpu.serve.sched import (
    AdmissionQueue,
    Backoff,
    Cancelled,
    DeadlineExceeded,
    QueueFull,
    RetriesExhausted,
    RetryAfter,
    ServeError,
    ServiceClosed,
)

__all__ = [
    "Request", "ResultHandle", "Service",
    "request_class_key", "horizon_bucket_of",
    "ServeError", "QueueFull", "ServiceClosed", "Cancelled",
    "DeadlineExceeded", "RetriesExhausted", "Backoff",
]


def _default_summary_path():
    from cimba_tpu.runner import experiment as ex

    return ex.default_summary_path


def horizon_bucket_of(t_end, horizon_bucket) -> object:
    """Which horizon bucket a ``t_end`` falls into — the Tier-B packing
    ladder (docs/14_wave_packing.md).  Module-level: the fleet router
    (docs/20_fleet.md) co-locates requests by the SAME class definition
    the dispatcher packs by, so the two can never drift.  Truncation is
    per-lane-exact regardless of who shares the wave; bucketing is
    purely the LATENCY policy bounding how much longer than its own
    horizon a request's wave may run."""
    if t_end is None:
        return "inf"
    t = float(t_end)
    if not t > 0.0:
        return "nonpos"
    if horizon_bucket is None:
        return "finite"
    import math

    return math.floor(math.log(t) / math.log(horizon_bucket))


def request_class_key(request, with_metrics: bool, *, mesh,
                      horizon_bucket) -> tuple:
    """What may share a wave — the compatibility CLASS of one
    :class:`Request`: the compiled-program class (spec structural
    fingerprint, profile, flags, pack arm, mesh —
    ``serve.cache.program_class_key``), the params tree signature
    (slices of both requests' params must concatenate), and the horizon
    bucket.  Seed, param VALUES, R, priority, the exact ``t_end``, and
    ``chunk_steps`` are per-lane data (or trajectory-invariant) and do
    not join the key; ``summary_path`` doesn't either, because each
    request folds its own slice through its own fold program.  ONE
    definition serves both the in-process :class:`Service` packer and
    the fleet router's co-location policy (docs/20_fleet.md)."""
    import jax

    from cimba_tpu.runner import experiment as ex

    pck = _pcache.program_class_key(
        request.spec, with_metrics, mesh=mesh, pack=request.pack,
    )
    shapes = jax.eval_shape(
        lambda: ex._slice_params(
            request.params, request.n_replications, 0, 1
        )
    )
    sig = (
        jax.tree.structure(shapes),
        tuple(
            (tuple(l.shape[1:]), str(l.dtype))
            for l in jax.tree.leaves(shapes)
        ),
    )
    return (pck, sig, horizon_bucket_of(request.t_end, horizon_bucket))


def fusion_class_key(request, with_metrics: bool, *, cache, mesh,
                     horizon_bucket) -> tuple:
    """The SECOND rung of the class ladder — what may share a **fused**
    wave (docs/26_wave_fusion.md): the spec's structural-geometry key
    with the model identity erased (``core.fuse.fusion_shape_key``),
    the full one-lane Sim STRUCTURE signature (user state, metrics and
    trace leaves, dtypes — so ``lax.switch`` branch structures can
    never mismatch), the params-row signature, the resolved trace-time
    globals, the mesh, and the horizon bucket.  Everything the exact
    class treats as per-lane data stays per-lane data here; what the
    exact class keys by *model identity* this key keys by model
    *shape*.  Raises :class:`cimba_tpu.core.fuse.FusionError` for
    structurally unfusable specs (spawn pools, boundary blocks) —
    callers treat that as "exact class only"."""
    from cimba_tpu import config as _config
    from cimba_tpu.core import fuse as _fuse
    from cimba_tpu.obs import trace as _trace

    shape = _fuse.fusion_shape_key(request.spec)
    sim_sig = _pcache.sim_structure_sig(
        cache, request.spec, request.params, request.n_replications,
        with_metrics, mesh=mesh, pack=request.pack,
    )
    psig = _pcache._params_sig(request.params, request.n_replications)
    return (
        shape, sim_sig, psig,
        _config.active_profile(), bool(with_metrics),
        request.pack if request.pack is not None
        else _config.xla_pack_enabled(),
        _trace.enabled(),
        _config.eventset_hier_enabled(), _config.eventset_block(),
        mesh, horizon_bucket_of(request.t_end, horizon_bucket),
    )


@dataclass
class Request:
    """One experiment request — the arguments of a direct
    :func:`cimba_tpu.runner.experiment.run_experiment_stream` call,
    plus serving policy (priority, deadline, label).

    ``wave_size=None`` uses the service's ``max_wave``; either way the
    effective wave size defines the request's slot partition, and the
    result is bitwise the direct call's at that same ``wave_size``.
    ``deadline`` is seconds from submission, checked at every dispatch
    boundary: a request whose deadline has expired when the dispatcher
    reaches it (initially or between its waves) fails with
    :class:`DeadlineExceeded`; work already running on the device is
    never interrupted — a deadline expiring mid-wave delivers that
    wave, then fails before the next.

    ``expect_digest`` (docs/18_audit.md): the result digest this
    request is EXPECTED to reproduce (e.g. from a stored run card,
    :func:`cimba_tpu.obs.audit.stream_result_digest`).  The result is
    delivered either way, but a mismatch bumps the service's
    ``digest_mismatches`` counter, marks the request's span tree, and
    flips ``/healthz`` to degraded — determinism regressions surface
    in the fleet's monitoring, not just in pytest.

    ``chunk_steps=None`` / ``pack=None`` (the defaults) resolve
    through the tuned-schedule registry at submit time
    (docs/21_autotune.md): with ``CIMBA_TUNE`` on and the service's
    program store carrying a searched winner for this (spec, backend,
    workload bucket), the winner's argument knobs fill in; otherwise
    the historical defaults (``chunk_steps=1024``, backend-auto pack)
    run unchanged.  Explicit values always win, and the resolution
    source (tuned/default/override) surfaces per class in
    ``Service.stats()["schedule"]`` and ``/varz``."""

    spec: Any
    params: Any
    n_replications: int
    seed: int = 0
    t_end: Optional[float] = None
    pack: Optional[bool] = None
    chunk_steps: Optional[int] = None
    wave_size: Optional[int] = None
    summary_path: Optional[Callable] = None
    priority: int = 0
    deadline: Optional[float] = None
    label: Optional[str] = None
    expect_digest: Optional[str] = None
    # cross-process trace grafting (docs/23_fleet_observability.md):
    # ``{"id": <remote trace id>, "parent": <remote span id>}`` — the
    # fleet slice fills this from the wire header so the request's span
    # tree grows under the router's, instead of starting a new trace.
    # None (the default) means a locally-rooted trace; ignored when the
    # service has no telemetry plane.  Never part of the class key.
    trace_context: Optional[dict] = None
    # multi-tenant QoS (docs/27_qos.md): who this request belongs to.
    # None = the registry's default tenant — exactly today's behavior.
    # Admission POLICY only (fair lane shares, quotas, rate limits,
    # deadline-class defaults): the tenant id is NEVER part of the
    # program/compatibility class key — two tenants' identical requests
    # share one compiled program, one wave, one bitwise digest.
    tenant: Optional[str] = None

    def __post_init__(self):
        if self.summary_path is None:
            self.summary_path = _default_summary_path()


class _Entry:
    """Dispatcher-internal per-request state (the queue stores these)."""

    __slots__ = (
        "request", "seq", "priority", "label", "cls", "eff_wave",
        "with_metrics", "next_lo", "acc", "n_waves", "retries", "solo",
        "cancelled", "in_flight", "submit_t", "first_dispatch_t",
        "deadline_at", "done", "result", "exc", "result_digest",
        "finish_t",
        "trace", "span_root", "span_queue", "span_wave",
        "fuse_cls", "spec_fp", "tenant",
    )

    def __init__(self, request, seq, cls, eff_wave, with_metrics):
        self.request = request
        self.seq = seq
        self.priority = request.priority
        self.label = request.label
        self.cls = cls
        self.eff_wave = eff_wave
        self.with_metrics = with_metrics
        self.next_lo = 0
        self.acc = None
        self.n_waves = 0
        self.retries = 0
        self.solo = False          # excluded from packing (retry isolation)
        self.cancelled = False
        self.in_flight = False
        self.submit_t = time.monotonic()
        self.first_dispatch_t = None
        self.deadline_at = (
            None if request.deadline is None
            else self.submit_t + request.deadline
        )
        self.done = threading.Event()
        self.result = None
        self.exc = None
        self.result_digest = None
        self.finish_t = None       # monotonic stamp set by _finish
        # telemetry span state — all None when the service has no
        # telemetry plane (the zero-allocation hot-submit contract)
        self.trace = None
        self.span_root = None
        self.span_queue = None
        self.span_wave = None
        # wave fusion (docs/26_wave_fusion.md): the fusion-class key
        # and the spec's in-memory fingerprint — both None unless the
        # service has fusion on AND the spec is fusable AND it joined
        # the class's member roster at submit
        self.fuse_cls = None
        self.spec_fp = None
        # resolved tenant id (docs/27_qos.md) — stamped at submit from
        # the service's registry (None request.tenant -> "default")
        self.tenant = None


class ResultHandle:
    """The future a :meth:`Service.submit` returns."""

    def __init__(self, service: "Service", entry: _Entry):
        self._service = service
        self._entry = entry

    @property
    def label(self) -> Optional[str]:
        return self._entry.label

    def done(self) -> bool:
        return self._entry.done.is_set()

    @property
    def finish_t(self) -> Optional[float]:
        """``time.monotonic()`` stamp of the moment the dispatcher
        retired this request (None while still in flight).  Load
        drivers pair it with their own monotonic submit stamp to get
        DELIVERY latency — a future collected long after it resolved
        must not read as slow (docs/27_qos.md measures per-tenant
        tails this way)."""
        return self._entry.finish_t

    def cancel(self) -> bool:
        """Cancel if still undispatched; returns False once any slot is
        in flight or the request already completed."""
        return self._service._cancel(self._entry)

    def exception(self, timeout: Optional[float] = None):
        if not self._entry.done.wait(timeout):
            raise TimeoutError(
                f"request {self._entry.label or self._entry.seq} not "
                f"done within {timeout}s"
            )
        return self._entry.exc

    def result(self, timeout: Optional[float] = None):
        """Block for the request's ``StreamResult`` (raises the
        structured serving error on failure)."""
        exc = self.exception(timeout)
        if exc is not None:
            raise exc
        return self._entry.result

    def digest(self, timeout: Optional[float] = None) -> str:
        """The completed result's bitwise digest
        (:func:`cimba_tpu.obs.audit.stream_result_digest`) — equal to
        the digest of the direct ``run_experiment_stream`` call at the
        same (spec, params, R, seed, wave_size), whoever shared the
        wave (the bitwise-isolation contract, docs/18_audit.md).
        Blocks like :meth:`result`; computed once and cached (the
        dispatcher already computed it when spans or ``expect_digest``
        were active)."""
        res = self.result(timeout)
        if self._entry.result_digest is None:
            from cimba_tpu.obs import audit as _audit

            self._entry.result_digest = _audit.stream_result_digest(res)
        return self._entry.result_digest


#: outcomes recorded in stats and trace spans
_OUTCOMES = ("completed", "failed", "cancelled", "deadline_exceeded")

#: refill-plane counters (docs/22_refill.md) — grouped in
#: ``stats()["refill"]`` and mirrored as ``cimba_serve_refill_*``
#: telemetry families
_REFILL_COUNTERS = (
    "refill_boundaries", "refill_admissions", "refill_retirements",
    "lanes_refilled", "lanes_reclaimed", "mid_wave_deliveries",
)

_DEVSCHED_COUNTERS = (
    "preemptions", "evictions", "restores", "sched_waves_started",
    "mem_rejects",
)

#: wave-fusion counters (docs/26_wave_fusion.md) — grouped in
#: ``stats()["fusion"]``: batches/waves actually dispatched through a
#: fused superprogram, the lanes they carried, and submits whose spec
#: could not join a fusion class (unfusable structure or a full roster)
_FUSION_COUNTERS = (
    "fused_batches", "fused_waves", "fused_lanes", "fusion_rejects",
)

#: per-tenant QoS counters (docs/27_qos.md) — grouped per tenant in
#: ``stats()["qos"]["tenants"]`` and mirrored as tenant-labeled
#: ``cimba_serve_qos_*`` telemetry families.  ``throttled`` splits by
#: reason (``throttled_rate`` + ``throttled_quota``); outcome counters
#: mirror the service-level ``_OUTCOMES`` names so per-tenant goodput
#: is ``completed / submitted`` with no new vocabulary.
_QOS_TENANT_COUNTERS = (
    "submitted", "admitted", "throttled", "throttled_rate",
    "throttled_quota", "completed", "failed", "cancelled",
    "deadline_exceeded", "claims", "lanes_claimed",
)


class _RefillSlot:
    """One request slot's lane ownership inside a refill-driven wave:
    the request entry, its replication window ``[lo, lo+n)``, and the
    wave lane indices it owns (ascending — lane order IS replication
    order, so the retirement fold gathers rows in exactly the order
    the direct call's contiguous wave slice has them)."""

    __slots__ = ("entry", "lo", "n", "lanes", "folded")

    def __init__(self, entry, lo, n):
        self.entry = entry
        self.lo = lo
        self.n = n
        self.lanes = []
        self.folded = False


class _RefillWave:
    """One refill-driven wave's bookkeeping: the ownership table
    (slots), the reclaimable free-lane pool (pad lanes at birth, plus
    every retired/killed slot's lanes), and the compiled programs the
    boundary controller dispatches (docs/22_refill.md)."""

    __slots__ = (
        "cls", "slots", "free", "L", "batch_no", "no_admit",
        "init_j", "chunk_j", "refill_j", "live_j", "pad_row",
        "fused", "sid_of",
    )

    def __init__(self, cls, no_admit):
        self.cls = cls
        self.slots = []
        self.free = []
        self.L = 0
        self.batch_no = 0
        self.no_admit = no_admit
        self.init_j = None
        self.chunk_j = None
        self.refill_j = None
        self.live_j = None
        self.pad_row = None
        # wave fusion (docs/26_wave_fusion.md): the FusedSpec bundle
        # this wave was born with (None = an ordinary single-spec
        # wave) and the member-fingerprint -> spec_id map the boundary
        # controller admits against.  The member set is FIXED at
        # birth: only specs in ``sid_of`` may splice in later (one
        # compiled superprogram per wave — a splice is never a
        # compile), so a roster that grew after birth reaches lanes
        # only through the next wave.
        self.fused = None
        self.sid_of = None


class Service:
    """A thread-based experiment service over one device (or mesh).

    ``max_wave`` bounds the lanes of one packed wave (one dispatch);
    ``max_pending`` bounds the admission queue (backpressure past it);
    ``cache`` is the shared :class:`~cimba_tpu.serve.cache.ProgramCache`
    (one is created if not given — pass your own to share warm programs
    with direct `run_experiment_stream` calls or across services);
    ``max_retries``/``backoff`` govern dispatch-failure retries;
    ``on_chunk`` is a per-chunk progress hook (bench.py's watchdog
    heartbeat).  Use as a context manager for a graceful shutdown.

    Packing policy knobs (docs/14_wave_packing.md):

    * ``pad_waves`` (default True): pad each packed wave's lane count
      up to a quantized shape — the next power-of-two multiple of the
      mesh device count, capped at ``max_wave`` — with dead masked
      lanes (``t_stop=-inf``; bitwise-inert for live lanes), so mixed
      traffic cycles a handful of compiled wave shapes instead of one
      compile per distinct fill level.  Padding waste is observable in
      ``stats()["lane_occupancy"]``.
    * ``horizon_bucket`` (default 16.0): requests pack only within a
      horizon bucket — finite ``t_end`` values bucket by
      ``floor(log(t_end)/log(horizon_bucket))`` and ``t_end=None``
      (run-to-completion) is its own bucket — bounding how long a
      short request can be held hostage by a long wave-mate to one
      bucket ratio.  ``None`` packs ALL finite horizons together
      (truncation stays exact either way; this is purely a latency
      policy).

    * ``refill`` (default None → the ``CIMBA_REFILL`` env knob, unset
      = off): continuous wave refill (docs/22_refill.md) — at every
      chunk boundary the dispatcher retires lanes whose owning request
      completed (folding and delivering THAT request immediately, not
      at whole-wave retirement), reclaims the lanes of cancelled /
      deadline-expired requests, and splices queued compatible
      requests into the freed lanes through a jitted, donated refill
      program — steady-state lane occupancy stays near-flat under
      mixed-horizon open-loop traffic instead of decaying over each
      wave's life, with zero recompiles after warmup.  Results stay
      bitwise the direct call's (lanes are independent; the splice is
      a masked per-lane re-init through the same init path).  Off,
      requests dispatch exactly as before — compiled chunk programs,
      packing, and results are identical; the only addition is the
      per-boundary liveness readback feeding the live occupancy gauge
      (service-local, never the shared program cache).

    * ``device_sched`` (default None → the ``CIMBA_DEVICE_SCHED`` env
      knob, unset = off): the preemptive device scheduler
      (docs/24_device_scheduler.md) — the dispatcher interleaves up to
      ``waves_per_device`` concurrent refill waves round-robin, one
      ``preempt_quantum`` of chunks each per turn; a new wave admits
      only when its estimated footprint fits the memory budget
      (``mem_budget_bytes``, default ``mem_fraction`` x device memory;
      a request that could NEVER fit fails fast with structured
      :class:`~cimba_tpu.serve.sched.MemoryBudgetExceeded`); and an
      urgent request may checkpoint-evict a strictly lower-priority
      wave at a quantum boundary and restore it bit-identically later
      (the PR 3 resumable-checkpoint path).  The three policy knobs
      left None resolve from a tuned schedule at submit time, else the
      ``tune.space`` defaults.  Off, dispatch is byte-identical to the
      refill/plain paths (the 'device_sched' trace gate pins this).

    * ``qos`` (default None → the ``CIMBA_QOS`` env knob, unset =
      off): the multi-tenant QoS plane (docs/27_qos.md) — freed refill
      lanes apportion across tenants by deficit-weighted round robin
      (``tenants``: a :class:`cimba_tpu.qos.TenantRegistry` of per-
      tenant weight / lane quota / rate limit / deadline class),
      equal-priority requests within a class order by earliest
      deadline (EDF), and a tenant past its quota or rate gets
      structured :class:`~cimba_tpu.serve.sched.RetryAfter` at submit
      instead of queueing.  Host-side admission POLICY only: the
      tenant never joins the class key, compiled programs are
      byte-identical either way (the 'qos' trace gate pins this), and
      every delivered result stays bitwise its direct solo call
      regardless of the admission order QoS chooses.  Off, admission
      is the historical priority-order prefix, byte for byte.

    ``telemetry`` (default None) attaches a
    :class:`cimba_tpu.obs.telemetry.Telemetry` plane: the background
    sampler scrapes :meth:`stats` into the time-series registry, the
    dispatcher loop heartbeats for ``/healthz`` liveness, request
    latencies feed the log2 histograms, and — with spans enabled — a
    ``trace_id`` minted at :meth:`submit` threads through
    admit → queue → pack → wave → chunk → fold → deliver as a JSONL
    span log (docs/17_telemetry.md).  None is strictly zero-cost: no
    threads, no span allocations, compiled programs untouched."""

    # cimba-check: must-hold(_lock) _counters, _outstanding, _seq, _closed, _stop, _occupancy, _class_ids, _spans, _depth_samples, _ttfw_sum, _ttfw_max, _ttfw_n, _sched_sources, _schedules, _occ_samples, _waves_live, _est_free_mem, _waves_per_device, _preempt_quantum, _mem_fraction, _mem_budget_bytes, _fuse_roster, _fuse_max_specs, _qos_lanes_held, _qos_tenant_counters, _qos_log, _qos_lat

    def __init__(
        self,
        *,
        max_wave: int = 4096,
        max_pending: int = 64,
        mesh=None,
        cache=None,
        max_retries: int = 2,
        backoff: Backoff = Backoff(),
        poll_every: int = 4,
        on_chunk: Optional[Callable] = None,
        trace_cap: int = 4096,
        pad_waves: bool = True,
        horizon_bucket: Optional[float] = 16.0,
        telemetry=None,
        refill: Optional[bool] = None,
        refill_every: Optional[int] = None,
        fuse: Optional[bool] = None,
        fuse_max_specs: Optional[int] = None,
        device_sched: Optional[bool] = None,
        waves_per_device: Optional[int] = None,
        preempt_quantum: Optional[int] = None,
        mem_fraction: Optional[float] = None,
        mem_budget_bytes: Optional[int] = None,
        qos: Optional[bool] = None,
        tenants=None,
        qos_clock: Optional[Callable[[], float]] = None,
        name: str = "cimba-serve",
    ):
        from cimba_tpu import config as _config

        if max_wave <= 0:
            raise ValueError(f"max_wave must be positive: {max_wave}")
        self.max_wave = int(max_wave)
        self.name = name
        self.mesh = mesh
        self.poll_every = poll_every
        # continuous wave refill (docs/22_refill.md): None defers to
        # the CIMBA_REFILL env knob (unset = off — the historical
        # dispatch path plus only the occupancy readback).  A
        # host-side dispatch policy only: compiled
        # chunk programs are identical either way (the 'refill' trace
        # gate pins this), and the refill/liveness programs live at
        # their own cache keys.
        self.refill = (
            _config.env_raw("CIMBA_REFILL") == "1" if refill is None
            else bool(refill)
        )
        # boundary-controller cadence: the controller's per-lane
        # liveness readback is a HOST SYNC (it must act on concrete
        # lane deaths), so running it every chunk would serialize the
        # async dispatch pipeline drive_chunks builds.  Every
        # ``refill_every`` chunks (default: poll_every — the same
        # depth the liveness poll already pipelines at) keeps the
        # pipeline full between control points; retirement/admission
        # latency is bounded by refill_every chunks.
        self.refill_every = max(
            int(poll_every if refill_every is None else refill_every), 1
        )
        # cross-spec wave fusion (docs/26_wave_fusion.md): None defers
        # to the CIMBA_WAVE_FUSE env knob (unset = off — the historical
        # one-spec-per-wave packer, byte for byte; the 'wave_fuse'
        # trace gate pins this).  A host-side dispatch policy like
        # refill/device_sched: ON, cross-spec requests of one fusion
        # class share a compiled superprogram whose per-lane spec-id
        # column switches each lane through its own model's blocks.
        # ``fuse_max_specs`` left None adopts a tuned schedule's value
        # at submit time, else tune.space.DEFAULT_FUSE_MAX_SPECS.
        self._fuse_unset = (
            fuse is None and _config.env_raw("CIMBA_WAVE_FUSE") == ""
        )
        self.fuse = (
            _config.env_raw("CIMBA_WAVE_FUSE") == "1" if fuse is None
            else bool(fuse)
        )
        self._fuse_max_specs = (
            None if fuse_max_specs is None else int(fuse_max_specs)
        )
        if self._fuse_max_specs is not None and self._fuse_max_specs < 2:
            raise ValueError(
                f"fuse_max_specs must be >= 2 (a fusion needs two "
                f"members to exist): {fuse_max_specs}"
            )
        # the fusion rosters: fusion-class key -> {spec fingerprint:
        # spec}, insertion-ordered, capped at the effective
        # fuse_max_specs.  The roster BINDS AT FIRST SIGHT: the first
        # fuse_max_specs distinct specs of a class are its members for
        # the service's life, so every fused wave of the class runs the
        # SAME superprogram (stable bundle -> zero steady-state
        # compiles); later distinct specs serve unfused.  Guarded by
        # the service lock.
        self._fuse_roster: dict = {}
        # the preemptive device scheduler (docs/24_device_scheduler.md):
        # None defers to the CIMBA_DEVICE_SCHED env knob (unset = off).
        # On, the dispatcher thread delegates to
        # serve.device.DeviceScheduler — concurrent refill waves per
        # device with memory-aware admission and checkpoint-evict-
        # restore preemption.  Host-side dispatch policy only, like
        # refill: compiled programs are byte-identical either way (the
        # 'device_sched' trace gate pins this).  The three policy knobs
        # stay None here when unset so a tuned schedule can adopt them
        # at submit time (_adopt_sched_knobs); effective defaults live
        # in tune.space (DEFAULT_WAVES_PER_DEVICE & co).
        self.device_sched = (
            _config.env_raw("CIMBA_DEVICE_SCHED") == "1"
            if device_sched is None else bool(device_sched)
        )
        self._waves_per_device = (
            None if waves_per_device is None else int(waves_per_device)
        )
        self._preempt_quantum = (
            None if preempt_quantum is None else int(preempt_quantum)
        )
        self._mem_fraction = (
            None if mem_fraction is None else float(mem_fraction)
        )
        self._mem_budget_bytes = (
            None if mem_budget_bytes is None else int(mem_budget_bytes)
        )
        if self._waves_per_device is not None \
                and self._waves_per_device <= 0:
            raise ValueError(
                f"waves_per_device must be positive: {waves_per_device}"
            )
        if self._mem_fraction is not None \
                and not 0.0 < self._mem_fraction <= 1.0:
            raise ValueError(
                f"mem_fraction must be in (0, 1]: {mem_fraction}"
            )
        # the multi-tenant QoS plane (docs/27_qos.md): None defers to
        # the CIMBA_QOS env knob (unset = off — admission is the PR 15
        # priority-order prefix, byte for byte; the 'qos' trace gate
        # pins ambient inertness).  On, freed refill lanes apportion
        # across tenants by deficit-weighted round robin, equal-
        # priority requests order by earliest deadline, and per-tenant
        # quotas/rate limits throttle at submit with structured
        # RetryAfter.  HOST-side admission policy only: the tenant id
        # never joins the class key, and delivered results stay bitwise
        # their direct solo calls regardless of admission order.
        # ``tenants`` is a qos.TenantRegistry (one is created if not
        # given — every tenant then runs the unlimited default policy,
        # fairly weighted); ``qos_clock`` injects the rate-limiter
        # clock (replay-determinism tests pin throttle logs under a
        # logical clock; production uses time.monotonic).
        from cimba_tpu.qos import (
            AdmissionLimiter as _QosLimiter,
            FairScheduler as _QosSched,
            TenantRegistry as _TenantRegistry,
        )

        self.qos = (
            _config.env_raw("CIMBA_QOS") == "1" if qos is None
            else bool(qos)
        )
        self._tenants = (
            tenants if tenants is not None else _TenantRegistry()
        )
        # DRR deficits: dispatcher-thread only (inside the queue's
        # take_selected lock) — needs no service lock
        self._qos_sched = _QosSched(self._tenants)
        self._qos_limiter = _QosLimiter(
            self._tenants,
            clock=time.monotonic if qos_clock is None else qos_clock,
        )
        self._qos_lanes_held: dict = {}      # tenant -> lanes in flight
        self._qos_tenant_counters: dict = {}  # tenant -> counter dict
        # the admission log the replay-determinism contract pins
        # (docs/27_qos.md): ("claim", tenant, seq, lanes) per fair-claim
        # admission and ("throttle", tenant, seq, lanes, reason) per
        # submit-time RetryAfter, in decision order
        self._qos_log = deque(maxlen=4096)
        # per-tenant completed-request latency window: what feeds the
        # stats()/telemetry p99 gauge — the victim-tail signal a QoS
        # dashboard watches under a flooding tenant
        self._qos_lat: dict = {}             # tenant -> deque[float]
        self.max_retries = int(max_retries)
        self.backoff = backoff
        self.cache = cache if cache is not None else _pcache.ProgramCache()
        self.pad_waves = bool(pad_waves)
        if horizon_bucket is not None and not horizon_bucket > 1.0:
            raise ValueError(
                f"horizon_bucket must be > 1 (a ratio), got "
                f"{horizon_bucket}"
            )
        self.horizon_bucket = horizon_bucket
        self._on_chunk = on_chunk
        self._queue = AdmissionQueue(max_pending)
        self._lock = threading.RLock()
        self._drained = threading.Condition(self._lock)
        self._outstanding = 0
        self._seq = 0
        self._closed = False
        self._stop = False
        self._t0 = time.monotonic()
        self._spans = deque(maxlen=trace_cap)
        self._depth_samples = deque(maxlen=trace_cap)
        self._counters = {
            "submitted": 0, "admitted": 0, "rejected": 0,
            "throttled": 0,
            "retries": 0, "batches": 0, "waves": 0,
            "lanes_dispatched": 0, "lanes_padded": 0,
            "digest_mismatches": 0,
        }
        for o in _OUTCOMES:
            self._counters[o] = 0
        for o in _REFILL_COUNTERS:
            self._counters[o] = 0
        for o in _DEVSCHED_COUNTERS:
            self._counters[o] = 0
        for o in _FUSION_COUNTERS:
            self._counters[o] = 0
        # per-chunk live-lane occupancy samples: (live, lanes_in_wave)
        # pairs appended at every chunk boundary — ``live`` is a host
        # int on the refill path (the boundary controller already
        # synced it) and a DEVICE [L] bool vector on the plain path
        # (the readback dispatch stays asynchronous; stats() converts
        # at scrape time).  This is what keeps
        # ``stats()["lane_occupancy"]`` live over a wave's life instead
        # of frozen at pack time (docs/22_refill.md).
        self._occ_samples = deque(maxlen=256)
        # free lanes in the in-flight refill wave RIGHT NOW — the
        # admission-headroom signal capacity-aware fleet placement
        # scrapes (docs/23_fleet_observability.md); 0 whenever no
        # refill wave is in flight (plain waves have no free pool)
        self._free_lanes = 0
        # device-scheduler aggregates (docs/24_device_scheduler.md):
        # live RUNNING waves and the estimated free device memory under
        # the admission budget — written by DeviceScheduler after every
        # wave-set change, scraped by stats()/fleet health
        self._waves_live = 0
        self._est_free_mem: Optional[int] = None
        # plain-path liveness-readback programs, per compatibility
        # class (dispatcher-thread only — see _run_batch)
        self._live_cache: dict = {}
        self._occupancy: dict = {}       # requests-per-batch -> count
        self._class_ids: dict = {}       # class key -> short label
        # tuned-schedule resolution accounting (docs/21_autotune.md)
        self._sched_sources = {
            "tuned": 0, "default": 0, "override": 0, "off": 0,
        }
        self._schedules: dict = {}       # class label -> resolved block
        self._ttfw_sum = 0.0
        self._ttfw_max = 0.0
        self._ttfw_n = 0
        # the host-side telemetry plane (docs/17_telemetry.md) — None
        # (the default) means zero overhead: no sampler thread, no span
        # objects on the submit path, nothing new on the dispatch path
        self._tel = telemetry
        self._tel_name = (
            telemetry.attach_service(self, name)
            if telemetry is not None else None
        )
        self._thread = threading.Thread(
            target=self._loop, name=name, daemon=True
        )
        self._thread.start()

    # -- client surface ------------------------------------------------------

    def submit(
        self, request: Request, *, block: bool = True,
        timeout: Optional[float] = None,
    ) -> ResultHandle:
        """Admit a request; returns its future.  ``block=True`` (the
        default) waits for queue space — the backpressure arm;
        ``block=False`` (or a ``timeout`` expiry) raises
        :class:`QueueFull` instead and counts an admission reject."""
        R = int(request.n_replications)
        if R <= 0:
            raise ValueError(f"n_replications must be positive, got {R}")
        eff_wave = min(
            R, self.max_wave if request.wave_size is None
            else int(request.wave_size),
        )
        # tuned-schedule resolution (docs/21_autotune.md): the ARGUMENT
        # knobs left unset resolve against the service's program store
        # at submit time, BEFORE the compatibility class binds — the
        # class must describe the program that will actually dispatch.
        # ``wave_size`` is passed as the already-effective value (a
        # Request's None has always meant "the service's max_wave", an
        # explicit policy, not an unset knob — a tuned wave_size never
        # applies here and never claims the 'tuned' source).  Trace-
        # time knobs — event-set layout — are process-level on the
        # serve path: set the CIMBA_EVENTSET_* env/config state the
        # tuner recommends; the dispatcher never flips globals under
        # concurrent traffic.  Explicit values always win.
        import dataclasses as _dc

        from cimba_tpu.tune import registry as _tune_reg

        _store = (
            self.cache._store
            if isinstance(self.cache, _pcache.ProgramCache) else None
        )
        rs = _tune_reg.resolve_entry(
            request.spec, R, pack=request.pack,
            chunk_steps=request.chunk_steps, wave_size=eff_wave,
            store=_store,
        )
        if (request.chunk_steps, request.pack) != (
            rs.chunk_steps, rs.pack
        ):
            # normalize a COPY — the caller's Request is never mutated
            request = _dc.replace(
                request, chunk_steps=rs.chunk_steps, pack=rs.pack,
            )
        if eff_wave <= 0:
            raise ValueError(
                f"wave_size must be positive, got {request.wave_size}"
            )
        if eff_wave > self.max_wave:
            raise ValueError(
                f"request wave_size={eff_wave} exceeds the service's "
                f"max_wave={self.max_wave} — it could never be scheduled"
            )
        if self.mesh is not None:
            n_dev = self.mesh.devices.size
            if R % n_dev or eff_wave % n_dev:
                raise ValueError(
                    f"n_replications={R} and wave_size={eff_wave} must "
                    f"divide evenly over {n_dev} devices"
                )
        from cimba_tpu.obs import metrics as _metrics

        with_metrics = _metrics.enabled()
        cls = self._class_key(request, with_metrics)
        # tuned fuse knobs adopt BEFORE the fusion class binds (a
        # schedule flipping fusion on must affect this very request);
        # device-sched knobs keep their historical adoption gate
        if rs.schedule is not None:
            with self._lock:
                if self.device_sched:
                    self._adopt_sched_knobs(rs.schedule)
                self._adopt_fuse_knobs(rs.schedule)
        # the fusion-class key (docs/26_wave_fusion.md) computes OUTSIDE
        # the lock — its Sim-structure signature eval_shapes on a cold
        # cache — and the roster binds under the lock below
        fuse_cls = None
        if self.fuse:
            from cimba_tpu.core import fuse as _fuse_mod

            try:
                fuse_cls = fusion_class_key(
                    request, with_metrics, cache=self.cache,
                    mesh=self.mesh, horizon_bucket=self.horizon_bucket,
                )
            except _fuse_mod.FusionError:
                fuse_cls = None
        with self._lock:
            if self._closed:
                raise ServiceClosed(
                    "service is draining/shut down — no new requests"
                )
            self._counters["submitted"] += 1
            self._seq += 1
            label = self._class_ids.setdefault(
                cls, f"class{len(self._class_ids)}"
            )
            self._sched_sources[rs.source] = (
                self._sched_sources.get(rs.source, 0) + 1
            )
            self._schedules[label] = rs.block()
            entry = _Entry(request, self._seq, cls, eff_wave,
                           with_metrics)
            entry.tenant = self._tenants.resolve(request.tenant)
            if self.qos:
                self._qos_tenant(entry.tenant)["submitted"] += 1
                if entry.deadline_at is None:
                    # the tenant's deadline_class stamps a default
                    # deadline on requests that carry none — what the
                    # EDF ordering within a class keys on (docs/27)
                    dc = self._qos_limiter.deadline_for(request.tenant)
                    if dc is not None:
                        entry.deadline_at = entry.submit_t + dc
            if self.fuse:
                self._bind_fusion(entry, fuse_cls)
            self._outstanding += 1
        rec = self._tel.spans if self._tel is not None else None
        if rec is not None:
            # the trace_id minted at submit — threaded through
            # admit → queue → pack → wave → chunk → fold → deliver.
            # The whole tree skeleton (root AND queue span) exists
            # BEFORE the entry is published to the queue: the moment
            # put() returns, the dispatcher may pack, run, and even
            # finish the request, and a span started after that would
            # resurrect the already-ended trace as a permanent leak.
            # cross-process grafting (docs/23_fleet_observability.md):
            # a request arriving over the fleet wire carries the
            # router's trace id + parent span — adopt them so this
            # process's tree hangs under the router's, instead of
            # minting a disconnected local trace
            ctx = request.trace_context
            remote_parent = None
            if ctx is not None and ctx.get("id"):
                remote_parent = (
                    str(ctx["parent"]) if ctx.get("parent") else None
                )
                entry.trace = rec.adopt_trace(
                    str(ctx["id"]), remote_parent
                )
            else:
                entry.trace = rec.new_trace()
            entry.span_root = rec.start(
                entry.trace, "request", parent=remote_parent,
                seq=entry.seq, label=entry.label,
                service=self._tel_name, lanes=R,
            )
            entry.span_queue = rec.start(
                entry.trace, "queue", parent=entry.span_root
            )
        if self.qos:
            # quota/rate admission control (docs/27_qos.md): a tenant
            # past its policy gets structured RetryAfter — never bare
            # QueueFull — naming the tenant, the reason, and a concrete
            # delay; nothing was admitted and the span tree closes
            # exactly once with the 'throttled' outcome, mirroring the
            # reject path below.  Checked under the service lock: the
            # lanes-held read and the token-bucket take must be atomic
            # against concurrent submits.
            try:
                with self._lock:
                    self._qos_limiter.check(
                        request.tenant, R,
                        self._qos_lanes_held.get(entry.tenant, 0),
                        label=entry.label,
                    )
            except RetryAfter as e:
                with self._lock:
                    self._outstanding -= 1
                    self._counters["throttled"] += 1
                    tc = self._qos_tenant(entry.tenant)
                    tc["throttled"] += 1
                    tc["throttled_" + e.reason] += 1
                    self._qos_log.append((
                        "throttle", entry.tenant, int(entry.seq),
                        int(R), e.reason,
                    ))
                    self._drained.notify_all()
                if self._tel is not None:
                    self._tel.observe_request(
                        self._tel_name, "throttled",
                        time.monotonic() - entry.submit_t, None,
                    )
                if rec is not None:
                    rec.end_trace(entry.trace, "throttled")
                raise
        try:
            self._queue.put(entry, block=block, timeout=timeout)
        except (QueueFull, ServiceClosed):
            with self._lock:
                self._outstanding -= 1
                self._counters["rejected"] += 1
                self._drained.notify_all()
            if rec is not None:
                rec.end_trace(entry.trace, "rejected")
            raise
        with self._lock:
            self._counters["admitted"] += 1
            if self.qos:
                self._qos_tenant(entry.tenant)["admitted"] += 1
                self._qos_lanes_held[entry.tenant] = (
                    self._qos_lanes_held.get(entry.tenant, 0) + R
                )
        if rec is not None:
            # instant marker only — safe after put even if the request
            # already completed (events never re-open a trace)
            rec.event(entry.trace, "admit", parent=entry.span_root)
        return ResultHandle(self, entry)

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until every admitted request has completed (a quiesce
        point; admission stays open).  Returns False on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while self._outstanding > 0:
                remaining = (
                    None if deadline is None
                    else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return False
                self._drained.wait(remaining)
            return True

    def shutdown(
        self, wait: bool = True, timeout: Optional[float] = None,
    ) -> None:
        """Stop admitting.  ``wait=True`` drains queued requests first
        (graceful); ``wait=False`` cancels everything still queued.
        Idempotent."""
        with self._lock:
            self._closed = True
        self._queue.close()
        if wait:
            self.drain(timeout)
        else:
            for entry in self._queue.drain_now():
                self._finish(entry, exc=Cancelled(entry.label),
                             outcome="cancelled")
        with self._lock:
            # CHK002: _stop is read by the dispatcher under the lock —
            # an unlocked write here could be reordered past the
            # dispatcher's claim
            self._stop = True
        self._queue.kick()
        self._thread.join(timeout)
        if self._tel is not None:
            # stop being observed: the plane takes a final stats
            # sample, then drops its collector and reference — a
            # long-lived Telemetry over a churn of services must not
            # pin or keep scraping shut-down ones (idempotent)
            self._tel.detach_service(self)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(wait=True)

    # -- observability -------------------------------------------------------

    def stats(self) -> dict:
        """Service-level metrics: counters, queue depth (+ high-water,
        and per compatibility class), batch-occupancy histogram
        (requests per packed wave), lane-level occupancy (live vs
        padded lanes — padding waste is observable, not just
        request-count occupancy), time-to-first-wave aggregate, and
        the shared program cache's hit/miss/eviction counters.

        Every value is read under either the service lock or the
        queue's one-acquisition :meth:`AdmissionQueue.snapshot`, so a
        scrape landing mid-dispatch is an atomic snapshot: the
        queue-depth total always equals the sum of its per-class
        breakdown, and the lane/occupancy counters always describe
        waves that were actually recorded together (the torn-read
        audit; tests/test_telemetry.py hammers this under live load).
        The dict IS the telemetry snapshot the background sampler
        scrapes into the ``/metrics`` registry (docs/17_telemetry.md)."""
        with self._lock:
            qs = self._queue.snapshot()
            out = dict(self._counters)
            out["queue_depth"] = qs["depth"]
            out["queue_depth_hwm"] = qs["depth_hwm"]
            out["queue_capacity"] = qs["capacity"]
            # every class ever seen reports, zeros included — a gauge
            # mirrored from this dict must drop to 0 when a class
            # drains, not stick at its last nonzero depth (the same
            # rule _class_sample applies to the chrome counter tracks)
            out["queue_depth_by_class"] = {
                label: qs["by_class"].get(c, 0)
                for c, label in sorted(
                    self._class_ids.items(), key=lambda cl: cl[1],
                )
            }
            out["classes_seen"] = len(self._class_ids)
            out["outstanding"] = self._outstanding
            out["batch_occupancy"] = dict(
                sorted(self._occupancy.items())
            )
            live = self._counters["lanes_dispatched"]
            padded = self._counters["lanes_padded"]
            out["lane_occupancy"] = {
                "lanes_live": live,
                "lanes_padded": padded,
                "padding_waste_frac": (
                    padded / (live + padded) if live + padded else 0.0
                ),
            }
            out["refill"] = {"enabled": self.refill}
            for k in _REFILL_COUNTERS:
                out["refill"][k] = self._counters[k]
            out["refill"]["free_lanes"] = self._free_lanes
            out["device_sched"] = {
                "enabled": self.device_sched,
                "waves_per_device": self._waves_per_device,
                "preempt_quantum": self._preempt_quantum,
                "mem_fraction": self._mem_fraction,
                "mem_budget_bytes": self._mem_budget_bytes,
                "waves_live": self._waves_live,
                "est_free_mem_bytes": self._est_free_mem,
            }
            for k in _DEVSCHED_COUNTERS:
                out["device_sched"][k] = self._counters[k]
            # the fusion rung (docs/26_wave_fusion.md): which fusion
            # classes formed, how full each roster is, and how much
            # traffic actually dispatched fused vs was rejected
            out["fusion"] = {
                "enabled": self.fuse,
                "fuse_max_specs": self._eff_fuse_max(),
                "classes": len(self._fuse_roster),
                "roster_sizes": sorted(
                    len(r) for r in self._fuse_roster.values()
                ),
            }
            for k in _FUSION_COUNTERS:
                out["fusion"][k] = self._counters[k]
            # the QoS plane (docs/27_qos.md): per-tenant counters
            # (goodput = completed/submitted), lanes currently held
            # against quota, the live DRR deficits, and the admission
            # log the replay-determinism contract compares
            qos_tenants = {}
            for t, c in sorted(self._qos_tenant_counters.items()):
                xs = sorted(self._qos_lat.get(t, ()))
                p99 = (
                    xs[min(len(xs) - 1,
                           int(round(0.99 * (len(xs) - 1))))]
                    if xs else 0.0
                )
                qos_tenants[t] = dict(c, latency_p99_s=p99)
            out["qos"] = {
                "enabled": self.qos,
                "tenants": qos_tenants,
                "lanes_held": dict(self._qos_lanes_held),
                "deficits": self._qos_sched.deficits(),
                "admission_log": [list(ev) for ev in self._qos_log],
            }
            occ_samples = list(self._occ_samples)
            out["time_to_first_wave"] = {
                "count": self._ttfw_n,
                "mean_s": (
                    self._ttfw_sum / self._ttfw_n if self._ttfw_n else 0.0
                ),
                "max_s": self._ttfw_max,
            }
            # which dispatch schedule each class runs, and where it
            # came from (docs/21_autotune.md) — ``/varz`` carries this
            # dict verbatim, so "is the fleet on the searched
            # schedule?" is one scrape away
            out["schedule"] = {
                "sources": dict(self._sched_sources),
                "by_class": dict(self._schedules),
            }
        # the live-occupancy view is computed OUTSIDE the lock: the
        # plain dispatch path stores device vectors (the readback stays
        # asynchronous), and forcing them to host must never stall a
        # concurrent submit/dispatch on the service lock
        import numpy as _np

        vals = []
        for v, tot in occ_samples:
            if not isinstance(v, int):
                v = int(_np.asarray(v).sum())
            vals.append((v, tot))
        fracs = [lv / t for lv, t in vals if t]
        last_live, last_tot = vals[-1] if vals else (0, 0)
        out["lane_occupancy"].update({
            "lanes_live_now": last_live,
            "lanes_in_wave": last_tot,
            "occupancy_now": last_live / last_tot if last_tot else 0.0,
            "occupancy_mean": (
                sum(fracs) / len(fracs) if fracs else 0.0
            ),
            "occupancy_samples": len(vals),
        })
        if hasattr(self.cache, "stats"):
            out["program_cache"] = self.cache.stats()
            # the persistent AOT store's hit/miss/downgrade counters,
            # surfaced top-level too (docs/15_program_store.md): a
            # fleet health check reads ONE dict to see whether rollouts
            # are serving from artifacts or silently recompiling
            store_stats = out["program_cache"].get("store")
            if store_stats is not None:
                out["program_store"] = store_stats
        return out

    def chrome_trace(self) -> dict:
        """Request lifecycle spans + queue-depth counter tracks (total
        and per compatibility class) + per-wave live/padded lane
        counters as a
        Chrome-trace / Perfetto dict (the same Trace Event Format schema
        ``obs.export`` emits, and it passes
        ``obs.export.validate_chrome_trace``): each request is one
        complete 'X' span on its own pid track, service stats ride in
        ``otherData.service``.  With a telemetry plane recording spans
        (docs/17_telemetry.md), each request's pid track additionally
        carries its queue/wave child spans and chunk/fold/deliver
        instants — the same span tree the JSONL log streams."""
        with self._lock:
            spans = list(self._spans)
            depths = list(self._depth_samples)
        children: dict = {}
        if self._tel is not None and self._tel.spans is not None:
            # the telemetry span trees (queue/wave spans, chunk/fold/
            # deliver instants) ride their request's pid track, one tid
            # per phase; the root "request" span is skipped — the
            # service's own lifecycle span below already draws it
            trace_pid = {
                s["trace"]: s["seq"] for s in spans
                if s.get("trace") is not None
            }
            tids = {"queue": 1, "wave": 2, "chunk": 3, "fold": 3,
                    "admit": 3, "deliver": 3}
            for e in self._tel.spans.chrome_events(
                self._t0,
                pid_of=trace_pid.get,
                tid_of=lambda n: tids.get(n, 4),
            ):
                if e["name"] != "request":
                    children.setdefault(e["pid"], []).append(e)
        events = []
        meta = []
        for s in spans:
            events.append({
                "name": s["label"] or f"request {s['seq']}",
                "ph": "X",
                "ts": (s["submit"] - self._t0) * 1e6,
                "dur": max((s["end"] - s["submit"]) * 1e6, 0.0),
                "pid": s["seq"],
                "tid": 0,
                "args": {
                    "outcome": s["outcome"],
                    "lanes": s["lanes"],
                    "time_to_first_wave_s": s["ttfw"],
                    "retries": s["retries"],
                },
            })
            # child spans spliced right after their root, sorted by ts:
            # every child starts at or after submit, so the pid track
            # stays timestamp-monotone (the validator's contract)
            events.extend(sorted(
                children.pop(s["seq"], ()), key=lambda e: e["ts"]
            ))
            meta.append({
                "name": "process_name", "ph": "M", "pid": s["seq"],
                "args": {"name": s["label"] or f"request {s['seq']}"},
            })
        # a live depth sample closes the counter tracks — and
        # guarantees at least one event, so an IDLE service still
        # exports a validator-clean trace; every seen class emits its
        # current (usually 0) depth so no track sticks at a stale value
        with self._lock:
            closing = self._class_sample()
        depths.append(
            (time.monotonic(), self._queue.depth(), closing, 0, 0)
        )
        for t, d, by_class, live, padded in depths:
            ts = (t - self._t0) * 1e6
            events.append({
                "name": "queue_depth", "ph": "C",
                "ts": ts, "pid": 0, "tid": 0,
                "args": {"depth": d},
            })
            # per-class queue-depth counter tracks (one track per
            # compatibility class) + the live/padded lane split of the
            # wave dispatched at this sample — padding waste as a
            # timeline, not just an aggregate
            for label, depth in by_class:
                events.append({
                    "name": f"queue_depth/{label}", "ph": "C",
                    "ts": ts, "pid": 0, "tid": 0,
                    "args": {"depth": depth},
                })
            if live or padded:
                events.append({
                    "name": "wave_lanes", "ph": "C",
                    "ts": ts, "pid": 0, "tid": 0,
                    "args": {"live": live, "padded": padded},
                })
        return {
            "traceEvents": events + meta,
            "displayTimeUnit": "ms",
            "otherData": {"service": self.stats()},
        }

    # -- internals -----------------------------------------------------------

    def _horizon_bucket(self, t_end):
        """This service's horizon bucket for ``t_end`` (the shared
        module-level :func:`horizon_bucket_of` at this service's
        ratio)."""
        return horizon_bucket_of(t_end, self.horizon_bucket)

    def _wave_shape(self, total: int) -> int:
        """The quantized lane count one wave of ``total`` live lanes
        dispatches at: the next power-of-two multiple of the mesh
        device count, capped at ``max_wave`` (pad-and-mask — the
        excess lanes are dead on arrival and bitwise-inert).  Disabled
        padding, or a cap that would under-shoot, returns ``total``
        unchanged."""
        if not self.pad_waves or total <= 0:
            return total
        unit = 1 if self.mesh is None else int(self.mesh.devices.size)
        q = unit
        while q < total:
            q *= 2
        q = min(q, self.max_wave)
        if q < total or (self.mesh is not None and q % unit):
            return total
        return q

    def _plan_pad(self, slots) -> tuple:
        """``(total live lanes, pad lanes)`` of one packed wave — the
        ONE definition both the stats recording (:meth:`_pack`) and the
        actual dispatch (:meth:`_run_batch`) use, so the counters can
        never describe a wave shape that wasn't dispatched."""
        total = sum(n for _, _, n in slots)
        return total, self._wave_shape(total) - total

    # cimba-check: assume-held
    def _class_sample(self) -> tuple:
        """Per-class queue depths over EVERY class ever seen (zeros
        included — a Chrome counter track holds its last value, so a
        drained class must emit 0 or it renders as stuck at its last
        nonzero depth forever).  Caller holds the service lock."""
        depths = self._queue.class_depths()
        return tuple(
            (label, depths.get(c, 0))
            for c, label in self._class_ids.items()
        )

    def _class_key(self, request: Request, with_metrics: bool) -> tuple:
        """This service's compatibility class for ``request`` — the
        shared module-level :func:`request_class_key` at this service's
        mesh and horizon-bucket ratio."""
        return request_class_key(
            request, with_metrics, mesh=self.mesh,
            horizon_bucket=self.horizon_bucket,
        )

    def _cancel(self, entry: _Entry) -> bool:
        with self._lock:
            if entry.done.is_set():
                return False
            if entry.in_flight:
                if not (self.refill or self.device_sched):
                    return False
                # refill/device-sched mode: an in-flight request's
                # lanes are freed
                # at the NEXT chunk boundary (flipped to t_stop=-inf —
                # reclaimable capacity), where the boundary controller
                # finishes it with Cancelled exactly once.  Best
                # effort: if every lane happens to die at that same
                # boundary, completion wins and the result is
                # delivered (docs/22_refill.md).
                entry.cancelled = True
                return True
            entry.cancelled = True
        # finish now (snappy futures); the dispatcher drops the
        # tombstone when it reaches it in the queue
        self._finish(entry, exc=Cancelled(entry.label),
                     outcome="cancelled")
        self._queue.kick()
        return True

    def _finish(self, entry: _Entry, *, result=None, exc=None,
                outcome: str) -> None:
        with self._lock:
            if entry.done.is_set():
                return
            entry.result = result
            entry.exc = exc
            now = time.monotonic()
            entry.finish_t = now
            self._counters[outcome] += 1
            ttfw = (
                None if entry.first_dispatch_t is None
                else entry.first_dispatch_t - entry.submit_t
            )
            self._spans.append({
                "seq": entry.seq,
                "label": entry.label,
                "submit": entry.submit_t,
                "end": now,
                "outcome": outcome,
                "lanes": entry.request.n_replications,
                "ttfw": ttfw,
                "retries": entry.retries,
                "trace": entry.trace,
            })
            if ttfw is not None:
                self._ttfw_sum += ttfw
                self._ttfw_max = max(self._ttfw_max, ttfw)
                self._ttfw_n += 1
            if self.qos and entry.tenant is not None:
                # quota release: the tenant's lanes free the moment the
                # request retires, whatever the outcome — and the
                # per-tenant outcome counter feeds the goodput gauges
                held = self._qos_lanes_held.get(entry.tenant, 0) \
                    - entry.request.n_replications
                if held > 0:
                    self._qos_lanes_held[entry.tenant] = held
                else:
                    self._qos_lanes_held.pop(entry.tenant, None)
                tc = self._qos_tenant(entry.tenant)
                if outcome in tc:
                    tc[outcome] += 1
                if outcome == "completed":
                    lat = self._qos_lat.get(entry.tenant)
                    if lat is None:
                        lat = deque(maxlen=512)
                        self._qos_lat[entry.tenant] = lat
                    lat.append(now - entry.submit_t)
            self._outstanding -= 1
            entry.done.set()
            self._drained.notify_all()
        tel = self._tel
        if tel is not None:
            tel.observe_request(
                self._tel_name, outcome, now - entry.submit_t, ttfw
            )
            if entry.trace is not None:
                rec = tel.spans
                rec.event(entry.trace, "deliver",
                          parent=entry.span_root, outcome=outcome)
                # closes any still-open queue/wave spans first — a
                # cancelled, deadline-expired, or retries-exhausted
                # request still yields one COMPLETE span tree
                rec.end_trace(entry.trace, outcome,
                              retries=entry.retries)

    # cimba-check: assume-held
    def _qos_tenant(self, name: str) -> dict:
        """The per-tenant QoS counter dict (created zeroed on first
        touch, so stats always reports full rows).  Caller holds the
        service lock."""
        tc = self._qos_tenant_counters.get(name)
        if tc is None:
            tc = {k: 0 for k in _QOS_TENANT_COUNTERS}
            self._qos_tenant_counters[name] = tc
        return tc

    # cimba-check: assume-held
    def _adopt_sched_knobs(self, sched) -> None:
        """Adopt a tuned schedule's device-scheduler policy knobs
        (docs/24_device_scheduler.md) for every knob the constructor
        left None — explicit constructor values always win, and the
        first adopted value sticks (one service, one policy; a later
        class resolving a different tuned schedule does not flap the
        scheduler mid-flight).  Caller holds the service lock."""
        if self._waves_per_device is None \
                and sched.waves_per_device is not None:
            self._waves_per_device = int(sched.waves_per_device)
        if self._preempt_quantum is None \
                and sched.preempt_quantum is not None:
            self._preempt_quantum = int(sched.preempt_quantum)
        if self._mem_fraction is None \
                and sched.mem_fraction is not None:
            self._mem_fraction = float(sched.mem_fraction)

    # cimba-check: assume-held
    def _adopt_fuse_knobs(self, sched) -> None:
        """Adopt a tuned schedule's wave-fusion knobs
        (docs/26_wave_fusion.md): ``fuse`` fills in only when BOTH the
        constructor and the ``CIMBA_WAVE_FUSE`` env left it unset
        (explicit policy always wins), ``fuse_max_specs`` when the
        constructor left it None — and as with the device-scheduler
        knobs, the first adopted value sticks.  Caller holds the
        service lock."""
        if self._fuse_unset and getattr(sched, "fuse", None) is not None:
            self.fuse = bool(sched.fuse)
            self._fuse_unset = False
        if self._fuse_max_specs is None \
                and getattr(sched, "fuse_max_specs", None) is not None \
                and int(sched.fuse_max_specs) >= 2:
            self._fuse_max_specs = int(sched.fuse_max_specs)

    # cimba-check: assume-held
    def _eff_fuse_max(self) -> int:
        """The effective roster cap — the constructor/adopted value,
        else the ``tune.space`` default."""
        if self._fuse_max_specs is not None:
            return self._fuse_max_specs
        from cimba_tpu.tune import space as _tspace

        return _tspace.DEFAULT_FUSE_MAX_SPECS

    # cimba-check: assume-held
    def _bind_fusion(self, entry: _Entry, fuse_cls) -> None:
        """Bind one admitted entry to its fusion class: join (or match)
        the class roster — first ``fuse_max_specs`` distinct specs win,
        for the service's life — and stamp the entry's fusion identity.
        A spec that cannot fuse (``fuse_cls=None``) or arrives at a
        full roster counts a ``fusion_rejects`` and serves through its
        exact class unchanged.  Caller holds the service lock."""
        if fuse_cls is None:
            self._counters["fusion_rejects"] += 1
            return
        fp = _pcache.spec_fingerprint(entry.request.spec)
        roster = self._fuse_roster.setdefault(fuse_cls, {})
        if fp not in roster:
            if len(roster) >= self._eff_fuse_max():
                self._counters["fusion_rejects"] += 1
                return
            roster[fp] = entry.request.spec
        entry.fuse_cls = fuse_cls
        entry.spec_fp = fp

    def _fused_bundle(self, fuse_cls):
        """The cached FusedSpec bundle for a class's CURRENT roster —
        members in canonical (stable-fingerprint) order, so any arrival
        order of the same member set shares one superprogram.  Requires
        >= 2 roster members (a single-member class serves exact —
        fusing it would shadow the historical program for no gain);
        returns None otherwise.  Dispatcher thread only."""
        with self._lock:
            roster = self._fuse_roster.get(fuse_cls)
            specs = () if roster is None else tuple(roster.values())
        if len(specs) < 2:
            return None
        specs = tuple(sorted(specs, key=_pcache.fusion_order_key))
        return _pcache.get_fused(self.cache, specs)

    def _loop(self) -> None:
        if self.device_sched:
            # the preemptive device scheduler
            # (docs/24_device_scheduler.md) owns this thread: concurrent
            # refill waves, memory-aware admission, checkpoint-evict-
            # restore preemption.  Off, everything below is the
            # historical loop, byte for byte.
            from cimba_tpu.serve.device import DeviceScheduler

            DeviceScheduler(self).run()
            return
        while True:
            if self._tel is not None:
                # liveness: the dispatcher beats at least once per
                # queue poll (and per chunk, via the _run_batch hook),
                # which is what /healthz judges "stalled" against
                self._tel.heartbeat(f"serve.{self._tel_name}.dispatch")
            entry = self._queue.pop_ready(timeout=0.25)
            # one atomic read of the shutdown state per poll (CHK002):
            # _stop/_closed/_outstanding together decide the exit, and
            # a torn read could exit with a request still outstanding
            with self._lock:
                stopping = self._stop
                drained = self._closed and self._outstanding == 0
            if entry is None:
                if stopping or drained:
                    # a backoff-delayed retry may still sit in the
                    # delay heap (it failed after shutdown's
                    # drain_now): cancel it rather than strand its
                    # future forever
                    for e in self._queue.drain_now():
                        if not e.done.is_set():
                            self._finish(e, exc=Cancelled(e.label),
                                         outcome="cancelled")
                    return
                continue
            if stopping:
                # non-graceful shutdown: whatever is still being popped
                # (including a requeued multi-wave remainder) is
                # cancelled, not run to completion
                if not entry.done.is_set():
                    self._finish(entry, exc=Cancelled(entry.label),
                                 outcome="cancelled")
                continue
            with self._lock:
                if entry.done.is_set():  # cancelled tombstone
                    continue
                cancelled_flag = entry.cancelled
                if not cancelled_flag:
                    # CLAIM under the service lock: from here cancel()
                    # returns False — an entry is either cancelled
                    # while truly undispatched, or it runs; never both
                    entry.in_flight = True
            if cancelled_flag:
                # a mid-wave cancel whose entry was requeued before the
                # flag was honored (refill remainder race): finish it
                # instead of running a whole slot for a dead request
                self._finish(entry, exc=Cancelled(entry.label),
                             outcome="cancelled")
                continue
            now = time.monotonic()
            if entry.deadline_at is not None and now > entry.deadline_at:
                self._finish(
                    entry,
                    exc=DeadlineExceeded(
                        entry.request.deadline, now - entry.submit_t,
                        entry.label,
                    ),
                    outcome="deadline_exceeded",
                )
                continue
            if self.refill:
                # continuous wave refill (docs/22_refill.md): the wave
                # is driven chunk-by-chunk with a boundary controller
                # that retires finished requests' lanes early and
                # splices queued compatible requests into them —
                # failure containment lives inside (_batch_failed on
                # the still-active members; delivered results stay
                # delivered)
                self._serve_refill_wave(entry)
                continue
            slots, members, fused = self._pack(entry)
            try:
                # the fold is inside the guard too: a summary_path whose
                # SHAPE preflights fine but whose fold-trace raises (e.g.
                # a non-Summary statistic fed to the Pébay merge) must
                # fail the REQUESTS, never kill the dispatcher thread —
                # a dead dispatcher hangs every outstanding future
                # (the fused kwarg is only passed when set, so the
                # retry tests' _run_batch seams keep their signature)
                sims = (
                    self._run_batch(slots) if fused is None
                    else self._run_batch(slots, fused=fused)
                )
                self._fold_slots(slots, sims)
            except Exception as e:
                self._batch_failed(members, e)
                continue
            self._complete_members(members)

    def _pack(self, lead: _Entry):
        """Build one wave: the lead's slots first (its own wave
        partition — only whole slots, never clipped, so the fold stays
        bitwise the direct call's), then greedily fill remaining lanes
        with queued requests of the SAME compatibility class in
        priority order (the bucket-fill policy: seed/params/R/horizon
        mixes pack, docs/14_wave_packing.md) — and, with fusion on and
        the lead roster-bound, with queued requests of the lead's
        FUSION class (docs/26_wave_fusion.md: distinct specs, one
        switch-dispatch superprogram; returns the bundle as a third
        result, None when the packed members stay single-spec — a
        homogeneous wave dispatches the historical exact-class program
        even with fusion on).  The lead arrives
        already CLAIMED (in_flight, set by the loop under the service
        lock); fill candidates are claimed here the same way — one that
        was cancelled in the gap between leaving the queue and the
        claim is dropped, never dispatched (cancel() stays truthful)."""
        budget = self.max_wave

        def plan(entry) -> list:
            """The entry's whole-slot partition that fits the budget."""
            nonlocal budget
            out = []
            lo = entry.next_lo
            R = entry.request.n_replications
            while lo < R:
                n = min(entry.eff_wave, R - lo)
                if n > budget:
                    break
                out.append((lo, n))
                budget -= n
                lo += n
            return out

        slots = [(lead, lo, n) for lo, n in plan(lead)]
        members = [lead]
        planned: list = []
        if budget > 0 and not lead.solo:
            now = time.monotonic()
            dropped: list = []

            def want(e: _Entry) -> bool:
                if e.done.is_set():
                    return True      # cancelled tombstone: just remove
                if e.deadline_at is not None and now > e.deadline_at:
                    dropped.append(e)
                    return True
                if e.solo:
                    return False
                if e.cls != lead.cls and not (
                    lead.fuse_cls is not None
                    and e.fuse_cls == lead.fuse_cls
                ):
                    # neither the exact class nor (fusion on, both
                    # roster-bound) the lead's fusion class
                    return False
                p = plan(e)
                if not p:
                    return False
                planned.append((e, p))
                return True

            self._queue.take(want)
            for e in dropped:
                self._finish(
                    e,
                    exc=DeadlineExceeded(
                        e.request.deadline, now - e.submit_t, e.label,
                    ),
                    outcome="deadline_exceeded",
                )
        with self._lock:
            for e, p in planned:
                if e.done.is_set():  # cancelled before the claim: drop
                    continue
                e.in_flight = True
                members.append(e)
                slots.extend((e, lo, n) for lo, n in p)
            for e in members:
                if e.first_dispatch_t is None:
                    e.first_dispatch_t = time.monotonic()
            total, padded = self._plan_pad(slots)
            self._counters["batches"] += 1
            batch_no = self._counters["batches"]
            self._counters["waves"] += len(slots)
            self._counters["lanes_dispatched"] += total
            self._counters["lanes_padded"] += padded
            # a wave is FUSED only when its members actually span more
            # than one exact class (distinct specs); roster membership
            # guarantees the bundle below covers every packed member
            needs_fuse = any(m.cls != lead.cls for m in members)
            if needs_fuse:
                self._counters["fused_batches"] += 1
                self._counters["fused_lanes"] += total
            k = len(members)
            self._occupancy[k] = self._occupancy.get(k, 0) + 1
            self._depth_samples.append((
                time.monotonic(), self._queue.depth(),
                self._class_sample(), total, padded,
            ))
        rec = self._tel.spans if self._tel is not None else None
        if rec is not None:
            for e in members:
                if e.trace is None:
                    continue
                if e.span_queue is not None:
                    rec.end(e.span_queue)
                    e.span_queue = None
                e.span_wave = rec.start(
                    e.trace, "wave", parent=e.span_root,
                    batch=batch_no,
                    members=len(members), lanes=total, padded=padded,
                )
        fused = (
            self._fused_bundle(lead.fuse_cls) if needs_fuse else None
        )
        return slots, members, fused

    def _run_batch(self, slots, fused=None):
        """Dispatch ONE packed wave: init the concatenated lanes —
        per-slot replication indices, seed columns, horizon columns,
        and parameter rows, plus the dead pad lanes that quantize the
        wave shape — and drive the shared chunk program to completion.
        The wave runs at the LEAD's ``chunk_steps`` (chunking is
        trajectory-invariant, so mates with other budgets still get
        bitwise-exact results).  Separated out as the failure-injection
        seam for the retry tests.

        ``fused`` (a FusedSpec bundle) switches the wave onto the
        fusion superprogram (docs/26_wave_fusion.md): a per-slot
        spec-id column joins the lane data, init dispatches each lane
        through its member's own model, the chunk program is the
        merged spec's ordinary one, and the horizon column is ALWAYS
        materialized (bitwise-inert — ``t_stop=t_end`` reproduces the
        static cond and no result reads the leaf).  Folds are
        untouched: each request's slot still folds its own lanes
        through its own fold program, so results stay bitwise the solo
        run's."""
        import jax
        import jax.numpy as jnp

        from cimba_tpu.core.loop import drive_chunks
        from cimba_tpu.runner import experiment as ex

        from cimba_tpu.obs import metrics as _metrics

        lead = slots[0][0]
        req = lead.request
        cls_now = _pcache.program_class_key(
            req.spec, _metrics.enabled(), mesh=self.mesh, pack=req.pack,
        )
        if cls_now != lead.cls[0]:
            # the program CLASS (dtype profile, obs.metrics/trace
            # flags, eventset layout, the pack default...) was frozen
            # into the compatibility key at submit; tracing now under
            # drifted globals would cache a program whose behavior
            # contradicts that key (and silently serve it to every
            # later request at this key).  ValueError = permanent:
            # fail the request loudly instead.
            raise ValueError(
                "serve: a trace-time global (dtype profile, "
                "obs.metrics/obs.trace state, eventset layout, or the "
                "pack default) changed between this request's submit "
                "and its dispatch — the compatibility key binds at "
                "submit time; resubmit after settling the globals"
            )
        if fused is None:
            init_j, chunk_j = _pcache.get_programs(
                self.cache, req.spec, mesh=self.mesh, pack=req.pack,
                chunk_steps=req.chunk_steps,
                with_metrics=lead.with_metrics,
            )
            sid_of = None
        else:
            init_j, chunk_j = _pcache.get_fused_wave_programs(
                self.cache, fused, mesh=self.mesh, pack=req.pack,
                chunk_steps=req.chunk_steps,
                with_metrics=lead.with_metrics,
            )
            sid_of = {
                _pcache.spec_fingerprint(s): k
                for k, s in enumerate(fused.members)
            }
        # each member's summary_path preflights against ITS params
        # shapes (paths may differ — every request folds its own slice
        # through its own fold program); fingerprint-cached, so a warm
        # cache skips the re-trace.  On the fused path the member's
        # spec-id is pinned into an adapter so the preflight traces the
        # member's OWN init branch (the preflight key is per member
        # fingerprint either way).
        seen: set = set()
        for e, _, n in slots:
            if id(e) in seen:
                continue
            seen.add(id(e))
            if fused is None:
                member_init = init_j
            else:
                sid = self._entry_sid(sid_of, e)

                def member_init(r, s, t, p, _f=init_j, _sid=sid):
                    return _f(
                        r, s, t, jnp.full(r.shape, _sid, jnp.int32), p,
                    )

            _pcache.preflight_summary_path(
                self.cache, e.request.spec, member_init,
                e.request.summary_path, e.request.params,
                e.request.n_replications, n, e.with_metrics,
            )
        total, pad = self._plan_pad(slots)
        reps = [jnp.arange(lo, lo + n) for _, lo, n in slots]
        seeds = [
            ex._seed_column(e.request.seed, n) for e, _, n in slots
        ]
        sids = (
            None if fused is None else [
                jnp.full((n,), self._entry_sid(sid_of, e), jnp.int32)
                for e, _, n in slots
            ]
        )
        if fused is None and pad == 0 and all(
            e.request.t_end is None for e, _, n in slots
        ):
            # unpadded all-run-to-completion wave: omit the t_stop leaf
            # entirely, like the direct stream path — the chunk cond
            # then skips the per-event horizon check (same program key;
            # jit re-specializes per pytree structure).  Fused waves
            # always carry the column (one program per class).
            t_stops = None
        else:
            t_stops = [
                ex._horizon_column(e.request.t_end, n)
                for e, _, n in slots
            ]
        pws = [
            ex._slice_params(
                e.request.params, e.request.n_replications, lo, n
            )
            for e, lo, n in slots
        ]
        if pad:
            # dead masked lanes: t_stop=-inf means the liveness cond is
            # false at entry — the lane never dispatches an event, and
            # its (sliced-off) state never joins any fold.  Its params
            # are the lead's row 0 (real, valid values, so user_init
            # cannot trip on them); rep/seed values are irrelevant.
            reps.append(jnp.zeros((pad,), reps[0].dtype))
            seeds.append(ex._seed_column(0, pad))
            t_stops.append(jnp.full((pad,), -jnp.inf, t_stops[0].dtype))
            if sids is not None:
                # dead lanes dispatch no events; member 0's init runs
                # on them only to produce a valid (masked-off) row
                sids.append(jnp.zeros((pad,), jnp.int32))
            row0 = ex._slice_params(
                req.params, req.n_replications, 0, 1
            )
            pws.append(jax.tree.map(
                lambda x: jnp.broadcast_to(x, (pad,) + x.shape[1:]),
                row0,
            ))
        if len(reps) == 1:
            reps_cat, seed_cat, pw_cat = reps[0], seeds[0], pws[0]
            ts_cat = None if t_stops is None else t_stops[0]
            sid_cat = None if sids is None else sids[0]
        else:
            reps_cat = jnp.concatenate(reps, axis=0)
            seed_cat = jnp.concatenate(seeds, axis=0)
            ts_cat = (
                None if t_stops is None
                else jnp.concatenate(t_stops, axis=0)
            )
            sid_cat = (
                None if sids is None else jnp.concatenate(sids, axis=0)
            )
            pw_cat = jax.tree.map(
                lambda *xs: jnp.concatenate(xs, axis=0), *pws
            )
        sims = (
            init_j(reps_cat, seed_cat, ts_cat, pw_cat) if fused is None
            else init_j(reps_cat, seed_cat, ts_cat, sid_cat, pw_cat)
        )
        on_chunk = self._on_chunk
        tel = self._tel
        if tel is not None:
            user_hook = self._on_chunk
            src = f"serve.{self._tel_name}.chunk"
            rec = tel.spans

            def on_chunk(n):
                # per-chunk telemetry tick (heartbeat + counter) and —
                # with spans on — an instant event on the LEAD's wave
                # span: the chunk leg of the request-scoped trace
                tel.tick(src)
                if rec is not None and lead.span_wave is not None:
                    rec.event(lead.trace, "chunk",
                              parent=lead.span_wave, n=n)
                if user_hook is not None:
                    user_hook(n)

        # per-chunk live-lane readback (docs/22_refill.md): a tiny
        # non-donated vmapped-cond dispatch per boundary feeds the live
        # ``lane_occupancy`` gauge — the device vector is stored as-is
        # (no host sync on the dispatch path; stats() converts at
        # scrape time), so /varz and the fleet health scraper see a
        # long wave's occupancy DECAY in real time instead of the
        # pack-time snapshot.  SERVICE-local cache, not the shared
        # ProgramCache: the readback is an observability detail of
        # this dispatcher, and it must not perturb the shared cache's
        # size/miss accounting ("a warmed service adds no program
        # entries" is a pinned contract); dispatcher-thread only, and
        # each entry pins its spec (the class key embeds function ids)
        live_key = (
            lead.cls if fused is None
            else ("fused",) + tuple(
                _pcache.spec_fingerprint(s) for s in fused.members
            )
        )
        ent = self._live_cache.get(live_key)
        if ent is None:
            from cimba_tpu.runner import experiment as ex

            live_spec = req.spec if fused is None else fused.spec
            pin = req.spec if fused is None else fused
            ent = (ex._live_program(live_spec, self.mesh), pin)
            self._live_cache[live_key] = ent
        live_j = ent[0]
        wave_lanes = total + pad
        every = self.refill_every

        def on_boundary(c, s, _live=live_j, _L=wave_lanes):
            if c % every:
                return None
            self._note_occupancy(_live(s), _L)
            return None

        return drive_chunks(
            chunk_j, sims, poll_every=self.poll_every,
            on_chunk=on_chunk, on_boundary=on_boundary,
        )

    def _note_occupancy(self, live, lanes: int) -> None:
        """Append one per-chunk occupancy sample — ``live`` is either a
        host int (refill boundaries, already synced) or a device [L]
        bool vector (the plain path's asynchronous readback)."""
        with self._lock:
            self._occ_samples.append((live, lanes))

    # -- continuous wave refill (docs/22_refill.md) --------------------------

    def _serve_refill_wave(self, lead: _Entry) -> None:
        """Drive ONE refill-managed wave to retirement: pack the lead
        (plus queued compatible requests, one whole slot each), then
        re-dispatch the shared chunk program with a boundary controller
        that (a) retires each request's lanes the chunk they die —
        folding THAT request through its own fold program and
        delivering its ResultHandle immediately, not at whole-wave
        retirement — (b) frees the lanes of cancelled / deadline-
        expired requests (flipped to ``t_stop=-inf`` pad capacity),
        and (c) splices queued compatible requests' (seed, t_stop,
        params) rows into freed lanes through the donated refill
        program — all without recompiling anything after warmup."""
        from cimba_tpu.core.loop import drive_chunks
        from cimba_tpu.obs import metrics as _metrics

        req = lead.request
        wave = None
        try:
            cls_now = _pcache.program_class_key(
                req.spec, _metrics.enabled(), mesh=self.mesh,
                pack=req.pack,
            )
            if cls_now != lead.cls[0]:
                raise ValueError(
                    "serve: a trace-time global (dtype profile, "
                    "obs.metrics/obs.trace state, eventset layout, or "
                    "the pack default) changed between this request's "
                    "submit and its dispatch — the compatibility key "
                    "binds at submit time; resubmit after settling "
                    "the globals"
                )
            wave = self._pack_refill(lead)
            sims = self._init_refill_wave(wave)
            on_chunk = self._on_chunk
            tel = self._tel
            if tel is not None:
                user_hook = self._on_chunk
                src = f"serve.{self._tel_name}.chunk"
                rec = tel.spans

                def on_chunk(n):
                    tel.tick(src)
                    if rec is not None and lead.span_wave is not None:
                        rec.event(lead.trace, "chunk",
                                  parent=lead.span_wave, n=n)
                    if user_hook is not None:
                        user_hook(n)

            every = self.refill_every

            def on_boundary(n, s):
                if n % every:
                    return None
                return self._refill_boundary(wave, n, s)

            sims = drive_chunks(
                wave.chunk_j, sims, poll_every=self.poll_every,
                on_chunk=on_chunk, on_boundary=on_boundary,
            )
            # final pass: every lane is dead — fold and deliver
            # whatever retired during the last (unpolled) chunks
            self._refill_boundary(wave, -1, sims, final=True)
        except Exception as e:
            with self._lock:
                self._free_lanes = 0   # no in-flight wave, no headroom
            members, seen = [], set()
            if wave is not None:
                for s in wave.slots:
                    e2 = s.entry
                    if s.folded or e2.done.is_set() or id(e2) in seen:
                        continue
                    seen.add(id(e2))
                    members.append(e2)
            else:
                members = [lead]
            if not members:
                # every member already delivered/finished before the
                # failure: nothing to fail — surface the error without
                # killing the dispatcher thread (a dead dispatcher
                # hangs every outstanding future)
                import warnings

                warnings.warn(
                    "serve refill: late wave error after every member "
                    f"delivered ({type(e).__name__}: {e})",
                    RuntimeWarning,
                )
                return
            self._batch_failed(members, e)

    def _refill_slot_size(self, entry: _Entry) -> int:
        """The entry's next WHOLE slot — ``min(eff_wave, R - next_lo)``,
        the same partition the direct ``run_experiment_stream`` call
        walks, so per-request folds stay bitwise the direct call's.
        Refill admits one slot per request at a time: a request's
        slots always fold in ``lo`` order (the accumulator's merge
        order is part of the bitwise contract)."""
        return min(
            entry.eff_wave,
            entry.request.n_replications - entry.next_lo,
        )

    @staticmethod
    def _entry_sid(sid_of: dict, entry: _Entry) -> int:
        """The entry's lane spec-id in a fused wave.  An entry claimed
        through the EXACT tier may predate its fusion binding
        (``spec_fp=None`` — e.g. submitted before a tuned schedule
        flipped fusion on); its exact class still pins the same spec as
        a roster member, so the fingerprint lookup cannot miss."""
        fp = entry.spec_fp
        if fp is None:
            fp = _pcache.spec_fingerprint(entry.request.spec)
        return sid_of[fp]

    def _claim_compatible(self, cls, budget: int, now: float, *,
                          strict_priority: bool, fuse_cls=None,
                          fuse_members=None) -> list:
        """The ONE queue scan both refill claim sites use (initial
        fill and boundary admission — one definition, so the paths
        cannot drift): take same-class entries, ONE whole slot each,
        in priority order, within ``budget`` lanes; drop cancelled
        tombstones and finish deadline-expired entries with
        ``DeadlineExceeded`` on the way.

        ``strict_priority=True`` is the boundary-admission fairness
        valve (docs/22_refill.md): only the priority-order PREFIX of
        compatible entries is taken — the first live entry of another
        class (or a solo retry) STOPS the scan, so a long-lived refill
        wave can never starve other classes by letting its own class
        jump the queue; with foreign work waiting, the wave stops
        admitting, drains, and retires (the same bound the plain
        dispatcher has).  Returns ``[(entry, n)]`` — NOT yet claimed;
        the caller marks ``in_flight`` under the service lock.

        ``fuse_cls`` widens compatibility to the wave's FUSION class
        (docs/26_wave_fusion.md): an entry of a different exact class
        still packs when its fusion class matches and — when
        ``fuse_members`` (the wave's member-fingerprint map) is given —
        its spec is one of the wave's superprogram members.  A
        fusion-class entry whose spec is NOT a member is foreign (it
        would need a different compiled superprogram): under
        strict_priority it trips the same fairness valve any other
        class does, so a stale fused wave drains instead of starving a
        grown roster."""
        if self.qos:
            # the QoS plane (docs/27_qos.md) swaps the priority-order
            # prefix for the deficit-weighted fair claim — same
            # compatibility and valve semantics, tenant-fair lanes
            return self._claim_fair(
                cls, budget, now, strict_priority=strict_priority,
                fuse_cls=fuse_cls, fuse_members=fuse_members,
            )
        planned: list = []
        dropped: list = []
        state = {"budget": int(budget), "blocked": False}

        def compatible(e: _Entry) -> bool:
            if e.cls == cls:
                return True
            if fuse_cls is None or e.fuse_cls != fuse_cls:
                return False
            if fuse_members is None:
                return True
            return e.spec_fp is not None and e.spec_fp in fuse_members

        def want(e: _Entry) -> bool:
            if e.done.is_set():
                return True      # cancelled tombstone: just remove
            if e.deadline_at is not None and now > e.deadline_at:
                dropped.append(e)
                return True
            if state["blocked"]:
                return False
            if e.solo or not compatible(e) or e.cancelled:
                if strict_priority:
                    state["blocked"] = True
                return False
            n = self._refill_slot_size(e)
            if n > state["budget"]:
                return False
            planned.append((e, n))
            state["budget"] -= n
            return True

        self._queue.take(want)
        for e in dropped:
            self._finish(
                e,
                exc=DeadlineExceeded(
                    e.request.deadline, now - e.submit_t, e.label,
                ),
                outcome="deadline_exceeded",
            )
        return planned

    def _claim_fair(self, cls, budget: int, now: float, *,
                    strict_priority: bool, fuse_cls=None,
                    fuse_members=None) -> list:
        """The QoS twin of :meth:`_claim_compatible` (docs/27_qos.md):
        identical compatibility, tombstone, and deadline semantics, and
        the SAME cross-class fairness valve under ``strict_priority`` —
        but the freed lanes apportion across TENANTS by the
        deficit-weighted round robin of
        :class:`cimba_tpu.qos.FairScheduler` (priority, then EDF, then
        fmix64 within a tenant) instead of going to the global
        priority-order prefix, so one flooding tenant's backlog can no
        longer occupy every freed lane.  The whole ready set is offered
        under the queue lock (``take_selected``) and the selection is
        pure host arithmetic: two fresh services replaying one stream
        produce identical admission logs (the determinism contract
        tests/test_qos.py pins)."""
        planned: list = []
        dropped: list = []

        def compatible(e: _Entry) -> bool:
            if e.cls == cls:
                return True
            if fuse_cls is None or e.fuse_cls != fuse_cls:
                return False
            if fuse_members is None:
                return True
            return e.spec_fp is not None and e.spec_fp in fuse_members

        def selector(offered):
            take: list = []
            cands: list = []
            blocked = False
            for e in offered:
                if e.done.is_set():
                    take.append(e)   # cancelled tombstone: just remove
                    continue
                if e.deadline_at is not None and now > e.deadline_at:
                    dropped.append(e)
                    take.append(e)
                    continue
                if blocked:
                    continue
                if e.solo or not compatible(e) or e.cancelled:
                    # the cross-class fairness valve is UNCHANGED by
                    # tenant fairness (docs/22_refill.md): foreign work
                    # still stops a boundary admission scan cold, so a
                    # long-lived wave drains instead of starving other
                    # classes — QoS reorders WITHIN the claimable set
                    if strict_priority:
                        blocked = True
                    continue
                cands.append(e)
            chosen = self._qos_sched.select(
                cands, int(budget),
                lanes_of=self._refill_slot_size,
                tenant_of=lambda e: (
                    e.tenant if e.tenant is not None
                    else self._tenants.resolve(None)
                ),
            )
            for e in chosen:
                planned.append((e, self._refill_slot_size(e)))
            take.extend(chosen)
            return take

        self._queue.take_selected(selector)
        if planned:
            with self._lock:
                for e, m in planned:
                    self._qos_log.append(
                        ("claim", e.tenant, int(e.seq), int(m))
                    )
                    tc = self._qos_tenant(e.tenant)
                    tc["claims"] += 1
                    tc["lanes_claimed"] += m
        for e in dropped:
            self._finish(
                e,
                exc=DeadlineExceeded(
                    e.request.deadline, now - e.submit_t, e.label,
                ),
                outcome="deadline_exceeded",
            )
        return planned

    def _pack_refill(self, lead: _Entry) -> _RefillWave:
        """The refill twin of :meth:`_pack`: build the initial wave —
        the lead's next whole slot plus queued same-class requests
        (ONE whole slot each, priority order) — and the per-lane
        request ownership table the boundary controller works against.
        Pad lanes are born into the free pool: reclaimable capacity,
        not dead weight."""
        wave = _RefillWave(lead.cls, bool(lead.solo))
        # a refill wave is born FUSED whenever the lead's fusion class
        # has >= 2 roster members (docs/26_wave_fusion.md): even a
        # wave whose initial slots are single-spec runs the class
        # superprogram, so later boundary splices can admit ANY member
        # without retracing.  The member set — and hence the compiled
        # program — is frozen at birth (wave.sid_of).
        if not lead.solo and lead.fuse_cls is not None:
            wave.fused = self._fused_bundle(lead.fuse_cls)
            if wave.fused is not None:
                wave.sid_of = {
                    _pcache.spec_fingerprint(s): k
                    for k, s in enumerate(wave.fused.members)
                }
        budget = self.max_wave - self._refill_slot_size(lead)
        planned: list = []
        if budget > 0 and not lead.solo:
            planned = self._claim_compatible(
                lead.cls, budget, time.monotonic(),
                strict_priority=False,
                fuse_cls=(
                    lead.fuse_cls if wave.fused is not None else None
                ),
                fuse_members=wave.sid_of,
            )
        members = [lead]
        with self._lock:
            slots = [_RefillSlot(
                lead, lead.next_lo, self._refill_slot_size(lead)
            )]
            for e, n in planned:
                if e.done.is_set():  # cancelled before the claim: drop
                    continue
                e.in_flight = True
                members.append(e)
                slots.append(_RefillSlot(e, e.next_lo, n))
            for e in members:
                if e.first_dispatch_t is None:
                    e.first_dispatch_t = time.monotonic()
            total = sum(s.n for s in slots)
            # a refill wave's shape is FROZEN for its whole (open-ended)
            # life, and under sustained load it never retires — a wave
            # born small would cap the service's throughput at its
            # birth shape forever.  With pad_waves on, refill waves are
            # therefore born at FULL quantized capacity: the pad lanes
            # are reclaimable admission headroom (t_stop=-inf, bitwise
            # inert), not waste (docs/22_refill.md).  pad_waves=False
            # keeps the exact packed shape (the latency-insensitive /
            # test-deterministic arm).
            if self.pad_waves and not wave.no_admit:
                cap = self.max_wave
                if self.mesh is not None:
                    unit = int(self.mesh.devices.size)
                    cap -= cap % unit
                pad = max(cap, total) - total
            elif self.pad_waves:
                # a solo (no-admit) wave can never USE admission
                # headroom — quantize like the plain path instead of
                # dispatching max_wave-wide chunks for nothing
                pad = self._wave_shape(total) - total
            else:
                pad = 0
            self._counters["batches"] += 1
            wave.batch_no = self._counters["batches"]
            self._counters["waves"] += len(slots)
            self._counters["lanes_dispatched"] += total
            self._counters["lanes_padded"] += pad
            if wave.fused is not None:
                self._counters["fused_waves"] += 1
                self._counters["fused_lanes"] += total
            k = len(members)
            self._occupancy[k] = self._occupancy.get(k, 0) + 1
            self._depth_samples.append((
                time.monotonic(), self._queue.depth(),
                self._class_sample(), total, pad,
            ))
        off = 0
        for s in slots:
            s.lanes = list(range(off, off + s.n))
            off += s.n
        wave.slots = slots
        wave.free = list(range(total, total + pad))
        wave.L = total + pad
        with self._lock:
            self._free_lanes = len(wave.free)
        rec = self._tel.spans if self._tel is not None else None
        if rec is not None:
            for e in members:
                if e.trace is None:
                    continue
                if e.span_queue is not None:
                    rec.end(e.span_queue)
                    e.span_queue = None
                e.span_wave = rec.start(
                    e.trace, "wave", parent=e.span_root,
                    batch=wave.batch_no, members=len(members),
                    lanes=total, padded=pad, refill=True,
                )
        return wave

    def _init_refill_wave(self, wave: _RefillWave):
        """Compile/fetch the wave's programs and init its lanes.  Like
        :meth:`_run_batch`'s init leg, except the per-lane ``t_stop``
        column is ALWAYS materialized (``t_end=None`` rides as
        ``+inf`` — bitwise the no-horizon cond, docs/14) because lane
        death, reclamation, and splicing are all horizon-driven."""
        import jax
        import jax.numpy as jnp

        from cimba_tpu.runner import experiment as ex

        lead = wave.slots[0].entry
        req = lead.request
        if wave.fused is None:
            wave.init_j, wave.chunk_j = _pcache.get_programs(
                self.cache, req.spec, mesh=self.mesh, pack=req.pack,
                chunk_steps=req.chunk_steps,
                with_metrics=lead.with_metrics,
            )
            wave.refill_j, wave.live_j = _pcache.get_refill_programs(
                self.cache, req.spec, mesh=self.mesh, pack=req.pack,
                with_metrics=lead.with_metrics,
            )
        else:
            # the fusion superprogram set (docs/26_wave_fusion.md):
            # spec-id-switched init/refill, the merged spec's ordinary
            # chunk/live programs — one compiled set per fusion class,
            # shared by every member
            wave.init_j, wave.chunk_j = _pcache.get_fused_wave_programs(
                self.cache, wave.fused, mesh=self.mesh, pack=req.pack,
                chunk_steps=req.chunk_steps,
                with_metrics=lead.with_metrics,
            )
            wave.refill_j, wave.live_j = (
                _pcache.get_fused_refill_programs(
                    self.cache, wave.fused, mesh=self.mesh,
                    pack=req.pack, with_metrics=lead.with_metrics,
                )
            )
        for s in wave.slots:
            self._preflight_wave_member(wave, s.entry, s.n)
        wave.pad_row = ex._slice_params(
            req.params, req.n_replications, 0, 1
        )
        reps, seeds, t_stops, sids, pws = [], [], [], [], []
        for s in wave.slots:
            e = s.entry
            reps.append(jnp.arange(s.lo, s.lo + s.n))
            seeds.append(ex._seed_column(e.request.seed, s.n))
            t_stops.append(ex._horizon_column(e.request.t_end, s.n))
            if wave.fused is not None:
                sids.append(jnp.full(
                    (s.n,), self._entry_sid(wave.sid_of, e), jnp.int32,
                ))
            pws.append(ex._slice_params(
                e.request.params, e.request.n_replications, s.lo, s.n
            ))
        pad = len(wave.free)
        if pad:
            reps.append(jnp.zeros((pad,), reps[0].dtype))
            seeds.append(ex._seed_column(0, pad))
            t_stops.append(jnp.full((pad,), -jnp.inf, t_stops[0].dtype))
            if wave.fused is not None:
                sids.append(jnp.zeros((pad,), jnp.int32))
            pws.append(jax.tree.map(
                lambda x: jnp.broadcast_to(x, (pad,) + x.shape[1:]),
                wave.pad_row,
            ))
        if len(reps) == 1:
            cat = (reps[0], seeds[0], t_stops[0], pws[0])
            sid_cat = sids[0] if sids else None
        else:
            cat = (
                jnp.concatenate(reps, axis=0),
                jnp.concatenate(seeds, axis=0),
                jnp.concatenate(t_stops, axis=0),
                jax.tree.map(
                    lambda *xs: jnp.concatenate(xs, axis=0), *pws
                ),
            )
            sid_cat = (
                jnp.concatenate(sids, axis=0) if sids else None
            )
        if wave.fused is None:
            return wave.init_j(*cat)
        return wave.init_j(cat[0], cat[1], cat[2], sid_cat, cat[3])

    def _preflight_wave_member(self, wave: _RefillWave, entry: _Entry,
                               n: int) -> None:
        """Preflight one member's ``summary_path`` against the wave's
        init program — on a fused wave through a spec-id adapter, so
        the trace runs the member's OWN init branch (the preflight
        cache key is per member fingerprint either way)."""
        import jax.numpy as jnp

        if wave.fused is None:
            member_init = wave.init_j
        else:
            sid = self._entry_sid(wave.sid_of, entry)
            init_j = wave.init_j

            def member_init(r, s, t, p, _f=init_j, _sid=sid):
                return _f(
                    r, s, t, jnp.full(r.shape, _sid, jnp.int32), p,
                )

        _pcache.preflight_summary_path(
            self.cache, entry.request.spec, member_init,
            entry.request.summary_path, entry.request.params,
            entry.request.n_replications, n, entry.with_metrics,
        )

    def _fold_refill_slot(self, s: _RefillSlot, sims) -> None:
        """Retire one slot: gather its lanes (ascending lane order ==
        replication order) and fold them through the REQUEST's own
        fold program — the same accumulator walk the direct call's
        contiguous wave slice takes, so the result stays bitwise."""
        import jax
        import jax.numpy as jnp

        e = s.entry
        fold_j = _pcache.get_fold(
            self.cache, e.with_metrics, e.request.summary_path,
        )
        sl = _pcache.get_gather(self.cache)(sims, jnp.asarray(s.lanes))
        if e.acc is None:
            e.acc = _pcache.stream_acc(e.request.spec, e.with_metrics)
        e.acc = fold_j(e.acc, sl)
        e.n_waves += 1
        e.next_lo = s.lo + s.n
        if e.trace is not None:
            self._tel.spans.event(
                e.trace, "fold", parent=e.span_wave, lo=s.lo, n=s.n,
            )

    def _refill_boundary(self, wave: _RefillWave, n: int, sims,
                         final: bool = False):
        """The boundary controller, fired after every chunk: read the
        per-lane liveness, retire slots whose lanes all died (fold +
        deliver / requeue the remainder), reclaim the lanes of
        cancelled and deadline-expired requests, and splice queued
        compatible admissions into the free pool.  Returns the
        respliced Sim when the wave changed (``drive_chunks`` then
        discards its stale liveness polls), else None."""
        import numpy as np

        import jax
        import jax.numpy as jnp

        from cimba_tpu.runner import experiment as ex

        live = np.asarray(wave.live_j(sims))
        with self._lock:
            self._counters["refill_boundaries"] += 1
            self._occ_samples.append((int(live.sum()), wave.L))
        rec = self._tel.spans if self._tel is not None else None
        now = time.monotonic()

        # 1) retire: fold slots whose lanes all died this chunk —
        # completion wins over a simultaneous cancel/deadline
        for s in wave.slots:
            e = s.entry
            if s.folded or e.done.is_set():
                continue
            if live[s.lanes].any():
                continue
            self._fold_refill_slot(s, sims)
            s.folded = True
            wave.free.extend(s.lanes)
            with self._lock:
                self._counters["refill_retirements"] += 1
                e.in_flight = False
            if rec is not None and e.span_wave is not None:
                rec.end(e.span_wave, outcome="ok")
                e.span_wave = None
            if e.next_lo >= e.request.n_replications:
                if not final:
                    with self._lock:
                        self._counters["mid_wave_deliveries"] += 1
                self._finish_completed(e)
            elif e.cancelled:
                # cancelled while its slot was draining: its lanes are
                # already free — finish NOW instead of requeueing the
                # remainder (a requeued remainder would be re-admitted
                # and burn a whole slot of device work before the
                # Cancelled landed)
                self._finish(e, exc=Cancelled(e.label),
                             outcome="cancelled")
            else:
                # remaining slots go back through the queue — the
                # admission scan below (or a later wave) picks the
                # next whole slot up
                if e.trace is not None:
                    e.span_queue = rec.start(
                        e.trace, "queue", parent=e.span_root,
                        requeue=True,
                    )
                self._queue.requeue(e)

        # 2) reclaim: free the lanes of cancelled / deadline-expired
        # requests — their lanes flip to t_stop=-inf pad capacity, and
        # the span tree closes exactly once with the right outcome
        kills: list = []
        for s in wave.slots:
            e = s.entry
            if s.folded or e.done.is_set():
                continue
            expired = e.deadline_at is not None and now > e.deadline_at
            if not (e.cancelled or expired):
                continue
            s.folded = True  # retired without a fold
            wave.free.extend(s.lanes)
            kills.extend(s.lanes)
            with self._lock:
                e.in_flight = False
                self._counters["lanes_reclaimed"] += s.n
            if rec is not None and e.span_wave is not None:
                rec.end(
                    e.span_wave,
                    outcome="cancelled" if e.cancelled else "deadline",
                )
                e.span_wave = None
            if e.cancelled:
                self._finish(e, exc=Cancelled(e.label),
                             outcome="cancelled")
            else:
                self._finish(
                    e,
                    exc=DeadlineExceeded(
                        e.request.deadline, now - e.submit_t, e.label,
                    ),
                    outcome="deadline_exceeded",
                )

        # 3) admit: splice queued compatible requests into free lanes
        admitted: list = []
        with self._lock:
            stopping = self._stop
        if not final and not stopping and wave.free and not wave.no_admit:
            # strict_priority: the fairness valve — a refill wave only
            # admits the priority-order PREFIX of compatible entries,
            # so queued work of OTHER classes (which cannot splice
            # into this wave) stops the refill instead of being
            # starved behind an endlessly-refilled wave; the wave
            # then drains and retires like a plain one
            # a fused wave admits any MEMBER spec of its frozen birth
            # roster (wave.sid_of) — later-grown roster entries are
            # foreign here, so the same strict_priority valve drains
            # the wave and the next one picks up the grown roster
            planned = self._claim_compatible(
                wave.cls, len(wave.free), now, strict_priority=True,
                fuse_cls=(
                    wave.slots[0].entry.fuse_cls
                    if wave.fused is not None else None
                ),
                fuse_members=wave.sid_of,
            )
            free_sorted = sorted(wave.free)
            with self._lock:
                for e, m in planned:
                    if e.done.is_set():
                        continue
                    e.in_flight = True
                    if e.first_dispatch_t is None:
                        e.first_dispatch_t = time.monotonic()
                    s = _RefillSlot(e, e.next_lo, m)
                    s.lanes = free_sorted[:m]
                    free_sorted = free_sorted[m:]
                    wave.slots.append(s)
                    admitted.append(s)
                    self._counters["refill_admissions"] += 1
                    self._counters["lanes_refilled"] += m
                    self._counters["waves"] += 1
                    self._counters["lanes_dispatched"] += m
            wave.free = free_sorted
            if rec is not None:
                for s in admitted:
                    e = s.entry
                    if e.trace is None:
                        continue
                    if e.span_queue is not None:
                        rec.end(e.span_queue)
                        e.span_queue = None
                    # the per-admission refill span (docs/22_refill.md)
                    sp = rec.start(
                        e.trace, "refill", parent=e.span_root,
                        boundary=n, batch=wave.batch_no, lanes=s.n,
                        lo=s.lo,
                    )
                    e.span_wave = rec.start(
                        e.trace, "wave", parent=e.span_root,
                        batch=wave.batch_no, refill=True, lanes=s.n,
                    )
                    rec.end(sp)
            for s in admitted:
                self._preflight_wave_member(wave, s.entry, s.n)

        with self._lock:
            # the scrapeable free-lane headroom tracks the pool across
            # retire/reclaim/admit; a retiring wave has no pool
            self._free_lanes = 0 if final else len(wave.free)

        if final or (not kills and not admitted):
            # (a final pass never splices — the wave is being retired,
            # and any killed entries were already finished above)
            return None

        # 4) splice: one donated refill dispatch re-seeds exactly the
        # masked lanes (admissions at their own (seed, horizon, rep,
        # params) rows; reclaimed lanes as t_stop=-inf pads)
        L = wave.L
        rep_dt = np.asarray(jnp.arange(1)).dtype
        mask = np.zeros((L,), bool)
        reps = np.zeros((L,), rep_dt)
        seeds = np.zeros((L,), np.uint64)
        ts = np.full(
            (L,), -np.inf,
            np.asarray(ex._horizon_column(None, 1)).dtype,
        )
        sids = np.zeros((L,), np.int32)
        if kills:
            mask[np.asarray(kills)] = True
        pw = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (L,) + x.shape[1:]),
            wave.pad_row,
        )
        for s in admitted:
            e = s.entry
            idx = np.asarray(s.lanes)
            mask[idx] = True
            reps[idx] = np.arange(s.lo, s.lo + s.n, dtype=rep_dt)
            seeds[idx] = np.uint64(e.request.seed)
            ts[idx] = np.asarray(
                ex._horizon_column(e.request.t_end, 1)
            )[0]
            if wave.fused is not None:
                sids[idx] = self._entry_sid(wave.sid_of, e)
            rows = ex._slice_params(
                e.request.params, e.request.n_replications, s.lo, s.n
            )
            jidx = jnp.asarray(idx)
            pw = jax.tree.map(
                lambda b, r, j=jidx: b.at[j].set(r), pw, rows
            )
        if wave.fused is None:
            return wave.refill_j(
                sims, jnp.asarray(mask), jnp.asarray(reps),
                jnp.asarray(seeds), jnp.asarray(ts), pw,
            )
        # the fused refill takes the per-lane spec-id column too:
        # killed lanes re-seed as sid-0 pads (t_stop=-inf keeps them
        # dead), admitted lanes as their member's own init branch
        return wave.refill_j(
            sims, jnp.asarray(mask), jnp.asarray(reps),
            jnp.asarray(seeds), jnp.asarray(ts), jnp.asarray(sids), pw,
        )

    def _fold_slots(self, slots, sims) -> None:
        """Slice the finished wave back per slot and fold each into its
        request's accumulator — in slot order, through the REQUEST's
        own fold program (``summary_path`` is per-request, not part of
        the compatibility class), so a multi-slot request folds exactly
        as its direct stream call would.  Pad lanes sit past the last
        slot's offset and are never sliced into any fold.  May raise
        (the fold traces user code); acc and next_lo advance together
        per slot, so a retry after a mid-batch failure resumes exactly
        at the first unfolded slot."""
        import jax.numpy as jnp

        off = 0
        for entry, lo, n in slots:
            fold_j = _pcache.get_fold(
                self.cache, entry.with_metrics,
                entry.request.summary_path,
            )
            sl = _pcache.get_gather(self.cache)(
                sims, jnp.arange(off, off + n)
            )
            if entry.acc is None:
                entry.acc = _pcache.stream_acc(
                    entry.request.spec, entry.with_metrics
                )
            entry.acc = fold_j(entry.acc, sl)
            entry.n_waves += 1
            entry.next_lo = lo + n
            off += n
            if entry.trace is not None:
                self._tel.spans.event(
                    entry.trace, "fold", parent=entry.span_wave,
                    lo=lo, n=n,
                )

    def _complete_members(self, members) -> None:
        """After a successful fold: finish done requests, requeue the
        rest.  No user code runs here — it must not raise (a raise
        after partial requeues could double-queue an entry)."""
        for entry in members:
            with self._lock:
                entry.in_flight = False
            if entry.trace is not None and entry.span_wave is not None:
                self._tel.spans.end(entry.span_wave, outcome="ok")
                entry.span_wave = None
            if entry.next_lo >= entry.request.n_replications:
                self._finish_completed(entry)
            else:
                # a request larger than one packed wave: remaining
                # slots go back through the queue at its own priority
                if entry.trace is not None:
                    entry.span_queue = self._tel.spans.start(
                        entry.trace, "queue", parent=entry.span_root,
                        requeue=True,
                    )
                self._queue.requeue(entry)

    def _finish_completed(self, entry: _Entry) -> None:
        """Deliver a fully-folded request's StreamResult — the same
        shape the direct ``run_experiment_stream`` call returns.

        Digest leg (docs/18_audit.md): when the request carries an
        ``expect_digest`` or the telemetry plane records spans, the
        result's bitwise digest is computed here (a host transfer of a
        few scalars) and recorded on the span tree; an expectation
        mismatch bumps ``digest_mismatches`` (the ``/healthz`` degraded
        signal) and marks the tree — the result is still delivered.
        With neither active, nothing is computed: results stay
        untouched device arrays (the zero-cost default)."""
        from cimba_tpu.runner.experiment import StreamResult

        acc = entry.acc
        result = StreamResult(
            summary=acc[0],
            n_failed=acc[1],
            total_events=acc[2],
            n_waves=entry.n_waves,
            n_regrows=0,
            metrics=acc[3] if entry.with_metrics else None,
        )
        expect = entry.request.expect_digest
        rec = self._tel.spans if self._tel is not None else None
        spans_on = rec is not None and entry.trace is not None
        if expect is not None or spans_on:
            from cimba_tpu.obs import audit as _audit

            dig = _audit.stream_result_digest(result)
            entry.result_digest = dig
            if spans_on:
                rec.event(
                    entry.trace, "digest", parent=entry.span_root,
                    digest=dig,
                )
            if expect is not None and expect != dig:
                with self._lock:
                    self._counters["digest_mismatches"] += 1
                if spans_on:
                    rec.event(
                        entry.trace, "digest_mismatch",
                        parent=entry.span_root, expected=expect,
                        got=dig,
                    )
        self._finish(entry, result=result, outcome="completed")

    def _batch_failed(self, members, exc: Exception) -> None:
        """Dispatch (or fold) failed.  Every member retries SOLO after
        exponential backoff — in the delay heap, so the dispatcher
        keeps serving other requests meanwhile.  The retry BUDGET is
        only charged for solo failures: when a PACKED batch fails,
        blame is unattributable, so members are demoted to solo and
        re-queued uncharged — an innocent request packed with a poison
        peer keeps its full budget of attributable solo attempts (and
        typically just succeeds on the first one).  ValueError/
        TypeError are treated as permanent (bad request, e.g. a
        summary_path that doesn't exist on the model) and surface
        immediately; anything else is presumed transient until the
        budget runs out.  ``stats()["retries"]`` counts every retry
        re-queue, charged or not."""
        permanent = isinstance(exc, (ValueError, TypeError))
        charged = len(members) == 1  # solo failure: blame attributable
        with self._lock:
            stopping = self._stop
        for entry in members:
            with self._lock:
                entry.in_flight = False
            if entry.trace is not None and entry.span_wave is not None:
                self._tel.spans.end(
                    entry.span_wave, outcome="error",
                    error=type(exc).__name__,
                )
                entry.span_wave = None
            if entry.next_lo >= entry.request.n_replications:
                # every one of ITS slots folded before the batch died
                # (a later member's fold failed): the result is whole —
                # deliver it; requeueing a slotless entry would crash
                # the next dispatch and discard computed work
                self._finish_completed(entry)
                continue
            with self._lock:
                entry.solo = True
                if charged:
                    entry.retries += 1
            if permanent:
                self._finish(entry, exc=exc, outcome="failed")
            elif charged and entry.retries > self.max_retries:
                err = RetriesExhausted(entry.retries, entry.label)
                err.__cause__ = exc
                self._finish(entry, exc=err, outcome="failed")
            elif stopping:
                # non-graceful shutdown already ran: a retry requeued
                # into the delay heap now could outlive the dispatcher
                # and strand its future — cancel instead
                self._finish(entry, exc=Cancelled(entry.label),
                             outcome="cancelled")
            else:
                with self._lock:
                    self._counters["retries"] += 1
                if entry.trace is not None:
                    entry.span_queue = self._tel.spans.start(
                        entry.trace, "queue", parent=entry.span_root,
                        retry=entry.retries, backoff=True,
                    )
                self._queue.requeue(
                    entry,
                    delay=self.backoff.delay(max(entry.retries, 1)),
                )
