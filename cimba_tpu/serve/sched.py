"""Admission control, deadlines, retries: the serving layer's queue.

Mirrors the Dynamical-Kernel-Scheduler decomposition (PAPERS.md):
request *admission* is decoupled from program *execution* behind a
bounded priority queue.  Everything here is host-side threading — no
jax — so the scheduling policy is testable without a device.

* :class:`AdmissionQueue` — a bounded priority queue with blocking
  backpressure (``put(block=True)`` waits for space; ``block=False``
  raises :class:`QueueFull` — the admission reject), a delayed-retry
  heap (:meth:`requeue` with a backoff delay keeps the entry OUT of
  the ready set until its retry time, so a failing request backs off
  without stalling the dispatcher), and a generic :meth:`take` scan
  the service uses to fill waves with compatible requests.
* :class:`Backoff` — deterministic exponential backoff (no jitter:
  reproducible schedules beat decorrelation at a single dispatcher).
* The structured error taxonomy: :class:`DeadlineExceeded`,
  :class:`Cancelled`, :class:`QueueFull`, :class:`ServiceClosed`,
  :class:`RetriesExhausted`, :class:`MemoryBudgetExceeded`,
  :class:`RetryAfter` — all subclasses of :class:`ServeError`, all
  carrying enough state to be actionable without parsing strings.

Ordering: higher ``priority`` pops first; ties break FIFO by admission
sequence number (a total order — the pack scan is deterministic).
Under the preemptive device scheduler (docs/24_device_scheduler.md)
the same ``priority`` is also the PREEMPTION policy: a claimed request
of strictly higher priority than the lowest-priority running wave may
checkpoint-evict that wave at its next quantum boundary, run, and have
the victim restored bit-identically — equal priority never preempts
(FIFO among peers), so the plain priority semantics are unchanged when
the scheduler is off.
"""

from __future__ import annotations

import heapq
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Tuple


class ServeError(Exception):
    """Base class of every structured serving error."""


class QueueFull(ServeError):
    """Admission rejected: the bounded queue is at capacity (and the
    caller declined to block, or its backpressure timeout expired)."""

    def __init__(self, capacity: int, label: Optional[str] = None):
        self.capacity = capacity
        self.label = label
        super().__init__(
            f"admission queue full (capacity {capacity})"
            + (f" — request {label!r} rejected" if label else "")
        )


class ServiceClosed(ServeError):
    """Submitted to a service that is draining or shut down."""


class Cancelled(ServeError):
    """The request was cancelled before it was dispatched."""

    def __init__(self, label: Optional[str] = None):
        self.label = label
        super().__init__(f"request {label!r} cancelled" if label else
                         "request cancelled")


class DeadlineExceeded(ServeError):
    """The request's deadline expired while it was still queued (or
    between dispatches of a multi-wave request).  Carries the deadline
    and the time actually waited — structured, not a string to parse."""

    def __init__(
        self, deadline_s: float, waited_s: float,
        label: Optional[str] = None,
    ):
        self.deadline_s = deadline_s
        self.waited_s = waited_s
        self.label = label
        super().__init__(
            f"deadline of {deadline_s:.3f}s exceeded after waiting "
            f"{waited_s:.3f}s"
            + (f" (request {label!r})" if label else "")
        )


class RetriesExhausted(ServeError):
    """Dispatch kept failing past the retry budget; the last failure is
    chained as ``__cause__``."""

    def __init__(self, attempts: int, label: Optional[str] = None):
        self.attempts = attempts
        self.label = label
        super().__init__(
            f"dispatch failed after {attempts} attempt(s)"
            + (f" (request {label!r})" if label else "")
        )


class MemoryBudgetExceeded(ServeError):
    """The request's wave could NEVER be admitted: its estimated device
    footprint (programs + lane buffers at the quantized wave shape)
    exceeds the device scheduler's whole memory budget on its own
    (docs/24_device_scheduler.md).  Structured backpressure — carries
    the estimate and the budget so a client can resize (smaller
    ``wave_size``) or route elsewhere, never a wrong program or a
    silent OOM.  A request that merely doesn't fit *right now* (budget
    held by live waves) is not an error: it waits, or preempts a
    lower-priority wave."""

    def __init__(
        self, needed_bytes: int, budget_bytes: int,
        label: Optional[str] = None,
    ):
        self.needed_bytes = int(needed_bytes)
        self.budget_bytes = int(budget_bytes)
        self.label = label
        super().__init__(
            f"estimated wave footprint {self.needed_bytes} B exceeds "
            f"the device memory budget {self.budget_bytes} B"
            + (f" (request {label!r})" if label else "")
        )


class RetryAfter(ServeError):
    """Admission throttled by the tenant's QoS policy (docs/27_qos.md):
    the tenant's token bucket is empty or its lane quota is saturated.
    Unlike bare :class:`QueueFull` this is *structured* backpressure —
    it names the tenant, the reason (``"rate"`` | ``"quota"``), and a
    concrete ``delay_s`` after which a retry can succeed, so a client
    can sleep exactly that long instead of guessing.  Other tenants'
    admission is untouched; the request was never admitted (nothing to
    cancel, no lanes held)."""

    def __init__(
        self, delay_s: float, tenant: str, reason: str = "rate",
        label: Optional[str] = None,
    ):
        self.delay_s = float(delay_s)
        self.tenant = str(tenant)
        self.reason = str(reason)
        self.label = label
        super().__init__(
            f"tenant {tenant!r} throttled ({reason}): retry after "
            f"{self.delay_s:.3f}s"
            + (f" (request {label!r})" if label else "")
        )


@dataclass(frozen=True)
class Backoff:
    """Deterministic exponential backoff: retry k (1-based) waits
    ``min(base * factor**(k-1), cap)`` seconds."""

    base: float = 0.05
    factor: float = 2.0
    cap: float = 2.0

    def delay(self, attempt: int) -> float:
        return min(self.base * self.factor ** max(attempt - 1, 0), self.cap)


@dataclass
class _Delayed:
    """Heap record for a backoff-delayed entry."""

    ready_at: float
    seq: int
    entry: Any = field(compare=False)

    def __lt__(self, other):  # heapq ordering
        return (self.ready_at, self.seq) < (other.ready_at, other.seq)


class AdmissionQueue:
    """Bounded priority queue + delayed-retry heap under one lock.

    Entries are opaque to the queue except for the attributes the
    service sets: ``priority`` (higher pops first), ``seq`` (FIFO
    tiebreak), and ``cls`` (the compatibility class, read only by the
    :meth:`class_depths` introspection) — the queue never inspects
    anything else; the pack policy lives in the service's :meth:`take`
    predicate.
    """

    # cimba-check: must-hold(_lock) _heap, _delayed, _closed, depth_hwm

    def __init__(self, capacity: int):
        if capacity <= 0:
            raise ValueError(f"queue capacity must be positive: {capacity}")
        self.capacity = capacity
        self._lock = threading.Lock()
        self._not_full = threading.Condition(self._lock)
        self._ready = threading.Condition(self._lock)
        self._heap: List[Tuple[Tuple[int, int], Any]] = []
        self._delayed: List[_Delayed] = []
        self._closed = False
        self.depth_hwm = 0

    # -- introspection -------------------------------------------------------

    def __len__(self):
        with self._lock:
            return len(self._heap) + len(self._delayed)

    def depth(self) -> int:
        return len(self)

    def class_depths(self) -> dict:
        """Queued entries (ready + backoff-delayed) per compatibility
        class — the ``cls`` attribute the service stamps on entries.
        Feeds ``Service.stats()['queue_depth_by_class']`` and the
        per-class Chrome-trace counter tracks, so a class starving
        behind another's traffic is visible.  Entries without a ``cls``
        (the queue stays generic) group under ``None``.  O(depth) scan
        under the lock: the queue is bounded by ``capacity``."""
        with self._lock:
            return self._class_depths_locked()

    def _class_depths_locked(self) -> dict:
        out: dict = {}
        for _, e in self._heap:
            c = getattr(e, "cls", None)
            out[c] = out.get(c, 0) + 1
        for d in self._delayed:
            c = getattr(d.entry, "cls", None)
            out[c] = out.get(c, 0) + 1
        return out

    def snapshot(self) -> dict:
        """``{"depth", "depth_hwm", "capacity", "by_class"}`` read under
        ONE lock acquisition — the atomic view ``Service.stats()`` (and
        the telemetry scraper behind ``/metrics``) reports, so a scrape
        landing mid-dispatch can never see a total depth that
        contradicts its own per-class breakdown (``depth`` is always
        exactly ``sum(by_class.values())``; the torn-read audit of
        docs/17_telemetry.md)."""
        with self._lock:
            by_class = self._class_depths_locked()
            return {
                "depth": len(self._heap) + len(self._delayed),
                "depth_hwm": self.depth_hwm,
                "capacity": self.capacity,
                "by_class": by_class,
            }

    # -- admission -----------------------------------------------------------

    def put(
        self, entry, *, block: bool = True,
        timeout: Optional[float] = None,
    ) -> None:
        """Admit ``entry``; blocks for space when full (backpressure)
        unless ``block=False``/timeout expiry, which raise
        :class:`QueueFull`."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while len(self._heap) + len(self._delayed) >= self.capacity:
                if self._closed:
                    raise ServiceClosed("service is shutting down")
                if not block:
                    raise QueueFull(
                        self.capacity, getattr(entry, "label", None)
                    )
                remaining = (
                    None if deadline is None
                    else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    raise QueueFull(
                        self.capacity, getattr(entry, "label", None)
                    )
                self._not_full.wait(remaining)
            if self._closed:
                raise ServiceClosed("service is shutting down")
            self._push(entry)
            self._ready.notify()

    # cimba-check: assume-held
    def _push(self, entry) -> None:
        heapq.heappush(self._heap, ((-entry.priority, entry.seq), entry))
        self.depth_hwm = max(
            self.depth_hwm, len(self._heap) + len(self._delayed)
        )

    def requeue(self, entry, *, delay: float = 0.0) -> None:
        """Return an entry to the queue (a multi-wave request between
        waves, or a failed dispatch backing off ``delay`` seconds).
        Bypasses the capacity check: the entry was already admitted —
        bouncing it on a full queue would lose it."""
        with self._lock:
            if delay > 0:
                heapq.heappush(
                    self._delayed,
                    _Delayed(time.monotonic() + delay, entry.seq, entry),
                )
                # the high-water mark tracks DEPTH (ready + delayed);
                # a backoff-delayed entry raises depth exactly like a
                # ready one, so it must ratchet the mark the same way
                # _push does — stats() would otherwise report a depth
                # above its own recorded maximum
                self.depth_hwm = max(
                    self.depth_hwm,
                    len(self._heap) + len(self._delayed),
                )
            else:
                self._push(entry)
            self._ready.notify()

    # -- the dispatcher side --------------------------------------------------

    # cimba-check: assume-held
    def _mature(self, now: float) -> None:
        """Move backoff-delayed entries whose time has come into the
        ready heap (caller holds the lock).

        Deadline override: an entry whose DEADLINE expired while it was
        serving its backoff delay matures immediately, ready_at or not —
        the dispatcher then fails it with ``DeadlineExceeded`` (waited
        time included) at the next dispatch boundary instead of holding
        the already-dead request through the rest of its backoff and
        burning a retry on it.  The scan is O(delayed) only when some
        entry actually carries a deadline; the delay heap is small by
        construction (failed requests, not the queue)."""
        while self._delayed and self._delayed[0].ready_at <= now:
            d = heapq.heappop(self._delayed)
            self._push(d.entry)
        if self._delayed and any(
            getattr(d.entry, "deadline_at", None) is not None
            for d in self._delayed
        ):
            keep = []
            matured = False
            for d in self._delayed:
                dl = getattr(d.entry, "deadline_at", None)
                if dl is not None and dl <= now:
                    self._push(d.entry)
                    matured = True
                else:
                    keep.append(d)
            if matured:
                self._delayed = keep
                heapq.heapify(self._delayed)

    def pop_ready(self, timeout: Optional[float] = None):
        """Pop the highest-priority ready entry, waiting up to
        ``timeout`` (and at most until the earliest delayed entry
        matures).  Returns None on timeout or close-with-empty."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                now = time.monotonic()
                self._mature(now)
                if self._heap:
                    entry = heapq.heappop(self._heap)[1]
                    self._not_full.notify()
                    return entry
                if self._closed and not self._delayed:
                    return None
                waits = []
                if deadline is not None:
                    if deadline - now <= 0:
                        return None
                    waits.append(deadline - now)
                if self._delayed:
                    waits.append(
                        max(self._delayed[0].ready_at - now, 0.0)
                    )
                    # wake for the earliest DEADLINE among delayed
                    # entries too: a deadline expiring mid-backoff
                    # matures the entry (see _mature), and an untimed
                    # pop must not sleep through that
                    dls = [
                        dl for d in self._delayed
                        if (dl := getattr(d.entry, "deadline_at", None))
                        is not None
                    ]
                    if dls:
                        waits.append(max(min(dls) - now, 0.0))
                self._ready.wait(min(waits) if waits else None)

    def take(self, want: Callable[[Any], bool]) -> List[Any]:
        """Remove and return every queued READY entry for which
        ``want(entry)`` is true, scanning in priority order — the
        service's wave-fill hook (``want`` closes over the lead
        request's compatibility key and the remaining lane budget; it
        must be cheap and must not touch the queue).  Backoff-delayed
        entries are not offered: they are serving their delay."""
        with self._lock:
            self._mature(time.monotonic())
            taken, kept = [], []
            for key, entry in sorted(self._heap):
                if want(entry):
                    taken.append(entry)
                else:
                    kept.append((key, entry))
            if taken:
                self._heap = kept
                heapq.heapify(self._heap)
                self._not_full.notify_all()
            return taken

    def take_selected(
        self, selector: Callable[[List[Any]], List[Any]],
    ) -> List[Any]:
        """Offer the WHOLE ready set (priority order) to ``selector``
        and remove exactly the entries it returns — the QoS wave-fill
        hook (docs/27_qos.md).  Where :meth:`take` commits to each
        entry with a single-pass predicate, a weighted-fair policy
        needs to see every candidate before choosing any (a flooding
        tenant's older requests must not pre-empt the scan); the
        selector runs under the queue lock, so it must be cheap, pure
        over its argument, and must not touch the queue.  Returns the
        selected entries in the selector's order.  Backoff-delayed
        entries are not offered: they are serving their delay."""
        with self._lock:
            self._mature(time.monotonic())
            offered = [entry for _, entry in sorted(self._heap)]
            taken = selector(offered)
            if taken:
                chosen = {id(e) for e in taken}
                self._heap = [
                    (key, entry) for key, entry in self._heap
                    if id(entry) not in chosen
                ]
                heapq.heapify(self._heap)
                self._not_full.notify_all()
            return taken

    # -- shutdown ------------------------------------------------------------

    def close(self) -> List[Any]:
        """Refuse further ``put``s.  Returns nothing; entries already
        queued stay queued (drain semantics — the dispatcher keeps
        popping until empty)."""
        with self._lock:
            self._closed = True
            self._ready.notify_all()
            self._not_full.notify_all()
            return []

    def drain_now(self) -> List[Any]:
        """Remove and return EVERY queued entry (ready and delayed) —
        the non-graceful shutdown path; the service fails them."""
        with self._lock:
            entries = [e for _, e in self._heap]
            entries += [d.entry for d in self._delayed]
            self._heap.clear()
            self._delayed.clear()
            self._not_full.notify_all()
            return entries

    def kick(self) -> None:
        """Wake a blocked ``pop_ready`` (state changed elsewhere)."""
        with self._lock:
            self._ready.notify_all()
