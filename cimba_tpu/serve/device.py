"""The preemptive device scheduler (docs/24_device_scheduler.md).

PR 15's boundary controller drove exactly ONE refill wave per device to
retirement: a request whose class matched no live wave waited for
whole-wave retirement even with device memory to spare.  This module
grows that controller into a device scheduler:

* **concurrent waves** — the dispatcher interleaves chunk dispatch
  across up to ``waves_per_device`` live waves, one preemption quantum
  (``preempt_quantum`` chunks) per wave per turn, round-robin.  Each
  wave is the PR 15 :class:`~cimba_tpu.serve.service._RefillWave`
  driven by the same boundary controller — retirement, reclamation,
  and boundary admission are unchanged, so every bitwise contract the
  refill plane pinned carries over verbatim.
* **memory-aware admission** — a new wave starts only when its
  estimated footprint (:func:`cimba_tpu.serve.cache
  .wave_footprint_bytes`: store-measured ``footprint_bytes`` →
  ``memory_analysis()`` → conservative estimate) fits the device
  budget (``mem_budget_bytes``, default ``mem_fraction`` x the
  device's reported memory).  A request whose wave could NEVER fit
  fails fast with structured
  :class:`~cimba_tpu.serve.sched.MemoryBudgetExceeded` backpressure;
  one that merely doesn't fit right now waits (or preempts).
* **wave preemption** — at a quantum boundary a lower-priority wave is
  checkpointed through the PR 3 resumable path
  (``runner.checkpoint.save_resumable``), its device buffers evicted,
  the urgent class runs, and the victim restores bit-identically: the
  Sim pytree is the COMPLETE per-lane state (counter-mode RNG
  position included), so a save/evict/restore round-trip is invisible
  to results — the determinism contract extended to scheduling.  The
  wave's host-side ownership table (``_RefillWave`` slots/free pool)
  is untouched by evict/restore, so retirements and mid-wave
  deliveries resume exactly where they left off.

Everything here is HOST-side dispatch policy: compiled programs are
byte-identical with the scheduler on or off (the ``device_sched``
gate in check/gates.py pins ambient inertness), and the scheduler
itself runs on the service's single dispatcher thread — no new
concurrency, the same lock discipline.
"""

from __future__ import annotations

import os
import time
import warnings
from typing import Optional

from cimba_tpu.serve import cache as _pcache
from cimba_tpu.serve.sched import Cancelled, MemoryBudgetExceeded
from cimba_tpu.tune.space import (
    DEFAULT_MEM_FRACTION,
    DEFAULT_PREEMPT_QUANTUM,
    DEFAULT_WAVES_PER_DEVICE,
)

__all__ = ["DeviceScheduler", "WaveTask", "device_memory_budget"]

#: fallback device memory when the backend reports none (CPU PjRt has
#: no ``bytes_limit``) — deliberately roomy: on such backends the
#: budget is a policy knob for tests/ops, not a hard physical wall
_DEFAULT_DEVICE_BYTES = 8 << 30

#: delay for a claimed request that fits nothing right now (budget or
#: wave slots held by equal/higher-priority waves): parked in the
#: queue's DELAYED heap — invisible to the boundary-admission fairness
#: valve while it waits, re-offered when capacity can have changed
_WAIT_REQUEUE_S = 0.05


def device_memory_budget(
    mem_fraction: Optional[float] = None,
    mem_budget_bytes: Optional[int] = None,
) -> int:
    """The admission budget in bytes: an explicit ``mem_budget_bytes``
    wins; otherwise ``mem_fraction`` (default
    ``tune.space.DEFAULT_MEM_FRACTION``) of the device's reported
    memory (``jax.devices()[0].memory_stats()`` where implemented,
    ``_DEFAULT_DEVICE_BYTES`` where not — CPU backends report
    nothing)."""
    if mem_budget_bytes is not None:
        return int(mem_budget_bytes)
    frac = float(
        DEFAULT_MEM_FRACTION if mem_fraction is None else mem_fraction
    )
    limit = None
    try:
        import jax

        stats = jax.devices()[0].memory_stats() or {}
        limit = (
            stats.get("bytes_limit")
            or stats.get("bytes_reservable_limit")
        )
    except Exception:
        limit = None
    if not limit:
        limit = _DEFAULT_DEVICE_BYTES
    return int(int(limit) * frac)


class WaveTask:
    """One live wave under the scheduler: the PR 15 ownership table
    (``wave``), its device state (``sims`` — None while PREEMPTED),
    the absolute chunk counter ``n`` (``drive_chunks`` resumes at
    ``n0=n``, so the boundary cadence ``n % refill_every`` is
    continuous across quanta AND across preempt/restore), the admitted
    footprint, and — while preempted — the checkpoint path plus the
    ``jax.eval_shape``-style aval template ``restore_resumable``
    rebuilds the pytree against."""

    RUNNING = "running"
    PREEMPTED = "preempted"

    __slots__ = (
        "wave", "sims", "n", "state", "footprint", "ckpt_path",
        "template",
    )

    def __init__(self, wave, sims, footprint: int):
        self.wave = wave
        self.sims = sims
        self.n = 0
        self.state = WaveTask.RUNNING
        self.footprint = int(footprint)
        self.ckpt_path = None
        self.template = None

    def priority(self) -> int:
        """The wave's CURRENT priority: the max over its live (unfolded,
        undelivered) members — a wave is as urgent as its most urgent
        member, so admitting an urgent request into a background wave
        also shields that wave from preemption."""
        best = None
        for s in self.wave.slots:
            if s.folded or s.entry.done.is_set():
                continue
            p = s.entry.priority
            if best is None or p > best:
                best = p
        return 0 if best is None else best

    def earliest_deadline(self) -> float:
        """The earliest ``deadline_at`` over the wave's live members
        (``+inf`` when none carries one) — the EDF refinement of the
        restore order (docs/27_qos.md): among equal-priority preempted
        waves, the one whose tightest live deadline expires first
        restores first, so a deadline-carrying wave does not burn its
        remaining budget parked behind a deadline-free peer."""
        best = float("inf")
        for s in self.wave.slots:
            if s.folded or s.entry.done.is_set():
                continue
            dl = s.entry.deadline_at
            if dl is not None and dl < best:
                best = dl
        return best


class DeviceScheduler:
    """The device-owner scheduling loop ``Service._loop`` delegates to
    when ``device_sched`` is on.  Runs ON the service's dispatcher
    thread and drives up to ``waves_per_device`` concurrent
    :class:`WaveTask`\\ s, one ``preempt_quantum`` of chunks each per
    round-robin turn; every quantum boundary is a control point for
    admission, preemption, and restore.  All wave mechanics (pack,
    init, boundary retire/reclaim/admit, failure containment) are the
    service's own refill methods — this class only decides WHICH wave
    runs next and WHETHER a new one may start."""

    def __init__(self, service):
        self.svc = service
        self.tasks: list = []     # WaveTasks, RUNNING + PREEMPTED
        self._rr = 0              # round-robin cursor over running waves
        self._ckpt_root = None    # lazily-created checkpoint spill dir
        self._budget_cache = None
        self._budget_frac = object()  # sentinel != any fraction

    # -- effective knobs (read lazily: submit-time schedule adoption
    # -- may fill them after this scheduler started) -------------------------

    def waves_per_device(self) -> int:
        with self.svc._lock:
            v = self.svc._waves_per_device
        return int(DEFAULT_WAVES_PER_DEVICE if v is None else v)

    def preempt_quantum(self) -> int:
        with self.svc._lock:
            v = self.svc._preempt_quantum
        return max(int(DEFAULT_PREEMPT_QUANTUM if v is None else v), 1)

    def budget_bytes(self) -> int:
        svc = self.svc
        with svc._lock:
            mb = svc._mem_budget_bytes
            mf = svc._mem_fraction
        if mb is not None:
            return int(mb)
        if mf != self._budget_frac:
            self._budget_cache = device_memory_budget(mf)
            self._budget_frac = mf
        return self._budget_cache

    # -- the loop ------------------------------------------------------------

    def run(self) -> None:
        """The scheduler's main loop — the device-sched twin of
        ``Service._loop``: poll the queue (non-blocking while waves
        are live), offer any claimed entry to admission, restore a
        preempted wave when capacity allows, then advance ONE running
        wave by one quantum.  Exits when stopping/drained with no live
        waves, cancelling stragglers exactly like the plain loop."""
        svc = self.svc
        try:
            while True:
                if svc._tel is not None:
                    svc._tel.heartbeat(
                        f"serve.{svc._tel_name}.dispatch"
                    )
                entry = svc._queue.pop_ready(
                    timeout=0.0 if self.tasks else 0.25
                )
                with svc._lock:
                    stopping = svc._stop
                    drained = svc._closed and svc._outstanding == 0
                if entry is None:
                    if not self.tasks and (stopping or drained):
                        for e in svc._queue.drain_now():
                            if not e.done.is_set():
                                svc._finish(
                                    e, exc=Cancelled(e.label),
                                    outcome="cancelled",
                                )
                        return
                elif stopping:
                    if not entry.done.is_set():
                        svc._finish(entry, exc=Cancelled(entry.label),
                                    outcome="cancelled")
                else:
                    self._offer_claimed(entry)
                self._maybe_restore()
                self._step_one()
        finally:
            self._cleanup_ckpt_root()

    # -- admission -----------------------------------------------------------

    def _offer_claimed(self, entry) -> None:
        """Claim ``entry`` (the plain loop's claim discipline) and
        route it: tombstone/cancel/deadline handling first, then the
        admission decision."""
        svc = self.svc
        with svc._lock:
            if entry.done.is_set():   # cancelled tombstone
                return
            cancelled_flag = entry.cancelled
            if not cancelled_flag:
                entry.in_flight = True
        if cancelled_flag:
            svc._finish(entry, exc=Cancelled(entry.label),
                        outcome="cancelled")
            return
        now = time.monotonic()
        if entry.deadline_at is not None and now > entry.deadline_at:
            from cimba_tpu.serve.sched import DeadlineExceeded

            svc._finish(
                entry,
                exc=DeadlineExceeded(
                    entry.request.deadline, now - entry.submit_t,
                    entry.label,
                ),
                outcome="deadline_exceeded",
            )
            return
        try:
            self._admit(entry)
        except Exception as e:
            # footprint estimation traces user code (eval_shape over
            # the init program): a bad request must fail ITSELF, never
            # kill the scheduler thread
            svc._batch_failed([entry], e)

    def _admit(self, entry) -> None:
        svc = self.svc
        # same-class live wave with slot headroom: unclaim and let the
        # boundary controller splice it — the bitwise-pinned PR 15
        # admission path, and no second wave of the same class
        slot = svc._refill_slot_size(entry)
        if not entry.solo:
            for t in self.tasks:
                if (t.state == WaveTask.RUNNING
                        and not t.wave.no_admit
                        and t.wave.cls == entry.cls
                        and len(t.wave.free) >= slot):
                    with svc._lock:
                        entry.in_flight = False
                    svc._queue.requeue(entry)
                    return
        fp = self._entry_footprint(entry)
        budget = self.budget_bytes()
        if fp > budget:
            # structured backpressure: this wave can NEVER fit —
            # resize or route elsewhere, never a wrong program
            with svc._lock:
                svc._counters["mem_rejects"] += 1
            svc._finish(
                entry,
                exc=MemoryBudgetExceeded(fp, budget, entry.label),
                outcome="failed",
            )
            return
        running = self._running()
        used = sum(t.footprint for t in running)
        if len(running) < self.waves_per_device() \
                and used + fp <= budget:
            self._start_wave(entry, fp)
            return
        # preemption: the lowest-priority running wave STRICTLY below
        # this entry yields its slot+memory at this quantum boundary —
        # and only when evicting it actually makes the entry fit
        victim = None
        victim_p = None
        for t in running:
            p = t.priority()
            if p >= entry.priority:
                continue
            if victim is None or p < victim_p:
                victim, victim_p = t, p
        if victim is not None \
                and used - victim.footprint + fp <= budget:
            self._preempt(victim)
            self._start_wave(entry, fp)
            return
        # no capacity right now (and nobody to preempt): wait in the
        # delayed heap — invisible to the boundary fairness valve, so
        # live waves keep admitting their own class meanwhile
        with svc._lock:
            entry.in_flight = False
        svc._queue.requeue(entry, delay=_WAIT_REQUEUE_S)

    def _entry_footprint(self, entry) -> int:
        """The entry's wave footprint at the shape its wave would
        actually be born at: full quantized capacity for an admitting
        wave (the _pack_refill birth policy), the quantized/solo slot
        otherwise."""
        svc = self.svc
        n = svc._refill_slot_size(entry)
        if svc.pad_waves and not entry.solo:
            cap = svc.max_wave
            if svc.mesh is not None:
                unit = int(svc.mesh.devices.size)
                cap -= cap % unit
            lanes = max(cap, n)
        elif svc.pad_waves:
            lanes = svc._wave_shape(n)
        else:
            lanes = n
        req = entry.request
        return _pcache.wave_footprint_bytes(
            svc.cache, req.spec, mesh=svc.mesh, pack=req.pack,
            chunk_steps=req.chunk_steps,
            with_metrics=entry.with_metrics, lanes=lanes,
            params=req.params, n_replications=req.n_replications,
        )

    def _start_wave(self, lead, footprint: int) -> None:
        """Pack + init a new wave for ``lead`` (the service's refill
        pack path — mates of the same class join immediately) and
        enroll it as a RUNNING task.  Failure containment mirrors
        ``_serve_refill_wave``: members not yet delivered fail through
        ``_batch_failed``; a wave whose members were all delivered
        before a late error only warns."""
        from cimba_tpu.obs import metrics as _metrics

        svc = self.svc
        req = lead.request
        wave = None
        try:
            cls_now = _pcache.program_class_key(
                req.spec, _metrics.enabled(), mesh=svc.mesh,
                pack=req.pack,
            )
            if cls_now != lead.cls[0]:
                raise ValueError(
                    "serve: a trace-time global (dtype profile, "
                    "obs.metrics/obs.trace state, eventset layout, or "
                    "the pack default) changed between this request's "
                    "submit and its dispatch — the compatibility key "
                    "binds at submit time; resubmit after settling "
                    "the globals"
                )
            wave = svc._pack_refill(lead)
            sims = svc._init_refill_wave(wave)
        except Exception as e:
            members, seen = [], set()
            if wave is not None:
                for s in wave.slots:
                    e2 = s.entry
                    if s.folded or e2.done.is_set() or id(e2) in seen:
                        continue
                    seen.add(id(e2))
                    members.append(e2)
            else:
                members = [lead]
            if not members:
                warnings.warn(
                    "serve device-sched: late wave error after every "
                    f"member delivered ({type(e).__name__}: {e})",
                    RuntimeWarning,
                )
            else:
                svc._batch_failed(members, e)
            self._update_gauges()
            return
        self.tasks.append(WaveTask(wave, sims, footprint))
        with svc._lock:
            svc._counters["sched_waves_started"] += 1
        self._update_gauges()

    # -- stepping ------------------------------------------------------------

    def _running(self) -> list:
        return [t for t in self.tasks if t.state == WaveTask.RUNNING]

    def _step_one(self) -> None:
        """Advance ONE running wave by one preemption quantum, round-
        robin — between any two quanta the loop returns to the queue,
        so admission/preemption latency is bounded by one quantum."""
        running = self._running()
        if not running:
            return
        task = running[self._rr % len(running)]
        self._rr += 1
        self._step(task)

    def _step(self, task: WaveTask) -> None:
        from cimba_tpu.core.loop import drive_chunks

        import numpy as np

        svc = self.svc
        wave = task.wave
        lead = wave.slots[0].entry
        state = {"n": task.n}
        user_hook = svc._on_chunk
        tel = svc._tel
        rec = tel.spans if tel is not None else None
        src = f"serve.{svc._tel_name}.chunk" if tel is not None else None

        def on_chunk(n):
            state["n"] = n
            if tel is not None:
                tel.tick(src)
                if rec is not None and lead.span_wave is not None:
                    rec.event(lead.trace, "chunk",
                              parent=lead.span_wave, n=n)
            if user_hook is not None:
                user_hook(n)

        every = svc.refill_every

        def on_boundary(n, s):
            if n % every:
                return None
            return svc._refill_boundary(wave, n, s)

        try:
            task.sims = drive_chunks(
                wave.chunk_j, task.sims, poll_every=svc.poll_every,
                on_chunk=on_chunk, on_boundary=on_boundary,
                max_chunks=self.preempt_quantum(), n0=task.n,
            )
            task.n = state["n"]
            # quantum boundary: retire the wave if every lane is dead
            # (the final boundary folds and delivers whatever the last
            # unpolled chunks finished)
            live = np.asarray(wave.live_j(task.sims))
            if not bool(live.any()):
                svc._refill_boundary(wave, -1, task.sims, final=True)
                self._retire(task)
        except Exception as e:
            self._fail_task(task, e)

    def _retire(self, task: WaveTask) -> None:
        self.tasks.remove(task)
        self._drop_ckpt(task)
        self._update_gauges()

    def _fail_task(self, task: WaveTask, exc: Exception) -> None:
        """A wave died mid-quantum: remove it and fail its undelivered
        members (the ``_serve_refill_wave`` containment, per-task)."""
        self.tasks.remove(task)
        self._drop_ckpt(task)
        members, seen = [], set()
        for s in task.wave.slots:
            e2 = s.entry
            if s.folded or e2.done.is_set() or id(e2) in seen:
                continue
            seen.add(id(e2))
            members.append(e2)
        if not members:
            warnings.warn(
                "serve device-sched: late wave error after every "
                f"member delivered ({type(exc).__name__}: {exc})",
                RuntimeWarning,
            )
        else:
            self.svc._batch_failed(members, exc)
        self._update_gauges()

    # -- preemption ----------------------------------------------------------

    def _preempt(self, task: WaveTask) -> None:
        """Checkpoint-evict ``task`` at the current quantum boundary:
        ``save_resumable`` the wave's Sim pytree (+ its absolute chunk
        counter as ``progress``), capture the aval template restore
        rebuilds against, then delete the device buffers.  The wave's
        HOST state — ownership slots, free-lane pool, accumulated
        per-request folds — rides the ``_RefillWave``/entries
        untouched, which is exactly why retirements and mid-wave
        deliveries resume unperturbed after restore."""
        import jax
        import numpy as np

        from cimba_tpu.runner import checkpoint as _ck

        svc = self.svc
        wave = task.wave
        path = os.path.join(
            self._ckpt_dir(), f"wave-{wave.batch_no}.ckpt"
        )
        task.template = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype),
            task.sims,
        )
        _ck.save_resumable(
            path, task.sims, progress=task.n,
            tag=f"devsched:{wave.batch_no}",
        )
        for leaf in jax.tree.leaves(task.sims):
            try:
                leaf.delete()
            except (RuntimeError, AttributeError):
                pass  # already-donated / non-Array leaf: GC takes it
        task.sims = None
        task.ckpt_path = path
        task.state = WaveTask.PREEMPTED
        with svc._lock:
            svc._counters["preemptions"] += 1
            svc._counters["evictions"] += 1
        rec = svc._tel.spans if svc._tel is not None else None
        if rec is not None:
            for s in wave.slots:
                e = s.entry
                if s.folded or e.done.is_set() or e.trace is None:
                    continue
                rec.event(e.trace, "preempt", parent=e.span_wave,
                          boundary=task.n, batch=wave.batch_no)
        self._update_gauges()

    def _maybe_restore(self) -> None:
        """Restore the most-urgent preempted wave when a slot AND
        budget free up — priority order (max live-member priority),
        NOT eviction order: an urgent wave preempted under earlier
        pressure must come back before a background wave that merely
        got evicted first.  Equal priority breaks by EDF — the wave
        whose earliest live-member ``deadline_at`` expires first
        restores first (deadline-aware restore, docs/27_qos.md; waves
        with no deadlines sort last) — then deterministically by
        ``fmix64(batch_no)`` (the obs/audit.py host mixer — arbitrary
        but stable, so equal-priority restore order is reproducible
        and owes nothing to list position).  With NO running wave the
        pick restores unconditionally (it fit when admitted; holding
        it back could deadlock the device idle)."""
        from cimba_tpu.obs.audit import _fmix64_host

        running = self._running()
        if len(running) >= self.waves_per_device():
            return
        preempted = [
            t for t in self.tasks if t.state == WaveTask.PREEMPTED
        ]
        if not preempted:
            return
        task = max(
            preempted,
            key=lambda t: (
                t.priority(), -t.earliest_deadline(),
                _fmix64_host(t.wave.batch_no),
            ),
        )
        if running:
            used = sum(t.footprint for t in running)
            if used + task.footprint > self.budget_bytes():
                return
        self._restore(task)

    def _restore(self, task: WaveTask) -> None:
        from cimba_tpu.runner import checkpoint as _ck

        svc = self.svc
        wave = task.wave
        sims, progress = _ck.restore_resumable(
            task.ckpt_path, task.template,
            tag=f"devsched:{wave.batch_no}",
        )
        task.sims = sims
        task.n = int(progress)
        task.state = WaveTask.RUNNING
        task.template = None
        self._drop_ckpt(task)
        with svc._lock:
            svc._counters["restores"] += 1
        rec = svc._tel.spans if svc._tel is not None else None
        if rec is not None:
            for s in wave.slots:
                e = s.entry
                if s.folded or e.done.is_set() or e.trace is None:
                    continue
                rec.event(e.trace, "restore", parent=e.span_wave,
                          boundary=task.n, batch=wave.batch_no)
        self._update_gauges()

    # -- bookkeeping ---------------------------------------------------------

    def _update_gauges(self) -> None:
        """Refresh the scrapeable aggregates after any wave-set change
        (the boundary controller writes per-wave ``_free_lanes``; with
        several live waves the scheduler owns the AGGREGATE)."""
        svc = self.svc
        running = self._running()
        used = sum(t.footprint for t in running)
        with svc._lock:
            svc._free_lanes = sum(
                len(t.wave.free) for t in running
            )
            svc._waves_live = len(running)
            svc._est_free_mem = max(self.budget_bytes() - used, 0)

    def _ckpt_dir(self) -> str:
        if self._ckpt_root is None:
            import tempfile

            self._ckpt_root = tempfile.mkdtemp(
                prefix="cimba-devsched-"
            )
        return self._ckpt_root

    def _drop_ckpt(self, task: WaveTask) -> None:
        if task.ckpt_path is not None:
            try:
                os.unlink(task.ckpt_path)
            except OSError:
                pass
            task.ckpt_path = None

    def _cleanup_ckpt_root(self) -> None:
        if self._ckpt_root is not None:
            import shutil

            shutil.rmtree(self._ckpt_root, ignore_errors=True)
            self._ckpt_root = None
