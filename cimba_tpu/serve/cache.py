"""The shared compiled-program cache behind streaming and serving.

``run_experiment_stream`` historically built its program table as an
unbounded per-call dict (the nested ``get_programs``): correct, but a
long-lived process sweeping many specs — exactly the serving shape —
accumulates one ``(init, chunk)`` program pair per (spec, settings)
point forever.  This module factors that table out into a **bounded,
thread-safe LRU** (:class:`ProgramCache`) plus the key builders both
:func:`cimba_tpu.runner.experiment.run_experiment_stream` and
:mod:`cimba_tpu.serve.service` share, so

* the stream runner's default cache is bounded (env
  ``CIMBA_PROGRAM_CACHE_CAP``, default 64 entries — generous: one entry
  per distinct (spec structure, profile, arm, mesh, chunk) point, not
  per wave shape; jit re-specializes per shape internally);
* the serving layer's *compatibility class* — which requests may share
  a wave — is definitionally a prefix of the key that selects a
  compiled program (:func:`program_class_key` vs :func:`program_key`),
  so "compatible" can never drift from "same program".  Seed, horizon,
  params values, and R are per-lane DATA columns, not program
  constants, so they appear in NEITHER key — the heterogeneous-wave
  contract of docs/14_wave_packing.md;
* hit/miss/eviction counters make cache health observable
  (:meth:`ProgramCache.stats`, surfaced by ``Service.stats()`` and the
  bench serve arm).

Entry-pinning invariant: every key that embeds object identities (the
structural fingerprint's block/handler/predicate function ids) stores
the spec object (or a tuple containing it) as part of its value, so a
cached id can never be recycled by the allocator while its entry lives.
Eviction drops the entry *and* its pin together — a later call with a
recycled id cannot hit a stale entry, because the stale entry is gone.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Callable, MutableMapping, Optional

#: environment knob for the default capacity; the default value (64 —
#: generous: entries are per (spec, settings) point, not per wave
#: shape) lives in ``config.ENV_KNOBS``, the one registry of knob
#: defaults
CAP_ENV = "CIMBA_PROGRAM_CACHE_CAP"


def default_capacity() -> int:
    from cimba_tpu import config

    cap = int(config.env_raw(CAP_ENV))
    if cap <= 0:
        raise ValueError(
            f"{CAP_ENV}={cap}: the program cache capacity must be "
            "positive (compiled programs are required to run at all)"
        )
    return cap


class ProgramCache(MutableMapping):
    """A bounded, thread-safe LRU mapping for compiled programs.

    Drop-in for the plain dict ``run_experiment_stream(program_cache=)``
    historically took (same mapping protocol), plus:

    * **bounded**: inserting past ``capacity`` evicts the
      least-recently-used entry (compiled programs are pure caches —
      an evicted point merely recompiles on next use);
    * **thread-safe**: every operation holds an internal lock, so a
      service dispatcher and direct-calling client threads can share
      one cache (the recommended deployment — shared warm programs);
    * **observable**: ``hits``/``misses``/``evictions`` counters and
      :meth:`stats` (misses are counted in :meth:`get_or_create`, the
      accessor the runner and service use).
    """

    # cimba-check: must-hold(_lock) _od, hits, misses, evictions

    def __init__(self, capacity: Optional[int] = None, *, store=None):
        self._cap = default_capacity() if capacity is None else int(capacity)
        if self._cap <= 0:
            raise ValueError(f"capacity must be positive, got {self._cap}")
        self._od: OrderedDict = OrderedDict()
        self._lock = threading.RLock()
        self._store = store
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    @property
    def store(self):
        """The persistent AOT program store behind this cache
        (docs/15_program_store.md): an explicit
        :class:`~cimba_tpu.serve.store.ProgramStore`, or — when the
        constructor got ``store=None`` — whatever
        ``CIMBA_PROGRAM_STORE`` names *right now* (resolved per lookup,
        so a cache built before the env var is irrelevant; pass
        ``store=False`` to opt a cache out entirely)."""
        if self._store is False:
            return None
        if self._store is not None:
            return self._store
        from cimba_tpu.serve import store as _pstore

        return _pstore.default_store()

    # -- mapping protocol ---------------------------------------------------

    def __getitem__(self, key):
        with self._lock:
            val = self._od[key]
            self._od.move_to_end(key)
            return val

    def __setitem__(self, key, val):
        with self._lock:
            self._od[key] = val
            self._od.move_to_end(key)
            while len(self._od) > self._cap:
                self._od.popitem(last=False)
                self.evictions += 1

    def __delitem__(self, key):
        with self._lock:
            del self._od[key]

    def __contains__(self, key):
        with self._lock:
            return key in self._od

    def __iter__(self):
        with self._lock:
            return iter(list(self._od))

    def __len__(self):
        with self._lock:
            return len(self._od)

    # -- the accessor the runner/service use --------------------------------

    def get_or_create(self, key, factory: Callable[[], Any]):
        """Return the cached value for ``key``, building it with
        ``factory()`` on a miss.  The factory runs OUTSIDE the lock (it
        may trace/compile for seconds — other threads must not block on
        it); if another thread won the race, its value wins and the
        duplicate build is discarded (benign: compiled programs are
        pure)."""
        with self._lock:
            if key in self._od:
                self.hits += 1
                self._od.move_to_end(key)
                return self._od[key]
        val = factory()
        with self._lock:
            if key in self._od:  # lost a benign build race
                self.hits += 1
                self._od.move_to_end(key)
                return self._od[key]
            self.misses += 1
            self[key] = val
            return val

    @property
    def capacity(self) -> int:
        return self._cap

    def stats(self) -> dict:
        """An atomic counter snapshot (one lock acquisition for every
        cache-local value — a scrape never sees a hits/misses pair from
        two different moments).  This dict is what the telemetry
        sampler mirrors into the ``cimba_program_cache_*`` metric
        families (docs/17_telemetry.md); ``hit_ratio`` is the
        cache-health headline, in the spirit of compiler-artifact
        caching stacks where hit ratio is a first-class signal."""
        with self._lock:
            lookups = self.hits + self.misses
            out = {
                "capacity": self._cap,
                "size": len(self._od),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "hit_ratio": self.hits / lookups if lookups else 0.0,
            }
        st = self.store
        if st is not None:
            out["store"] = st.stats()
        return out


def _get_or_create(programs: MutableMapping, key, factory):
    """``get_or_create`` against either a :class:`ProgramCache` or the
    plain dict legacy callers still pass."""
    if isinstance(programs, ProgramCache):
        return programs.get_or_create(key, factory)
    if key not in programs:
        programs[key] = factory()
    return programs[key]


def cached(programs: MutableMapping, key, factory):
    """Public get-or-create for SUBSYSTEM-specific compiled programs
    sharing the stream/serve cache (same dict-or-ProgramCache duality
    as the core accessors).  Callers namespace their keys with a
    leading tag — the sweep engine keys its serve-result merge as
    ``("sweep_serve_merge",)`` and its metrics reduce as
    ``("sweep_metrics_merge",)`` — and follow the entry-pinning
    invariant above: any object identity in the key must be kept alive
    by the cached value (a closure referencing the keyed object pins
    it)."""
    return _get_or_create(programs, key, factory)


# -- key builders (the stream runner's cache contract, factored out) --------


# cimba-check: content-path
def spec_fingerprint(spec) -> tuple:
    """STRUCTURAL identity of a ModelSpec for program keys.

    Function-valued structure — blocks, user handlers, ``user_init``,
    condition predicates — keys by object identity (``id``): what the
    tracer closes over IS the function object, so two specs sharing the
    same function objects and the same static data trace the same
    program.  That is exactly the ``dataclasses.replace`` twin shape
    (sweep drivers rebuilding a spec with an unchanged field set —
    ``replace`` copies the function references), which under the old
    ``id(spec)`` key could never share a cache slot.  A model re-built
    from source gets fresh function objects and merely recompiles —
    safe.  The id-recycling hazard is unchanged: every cache entry
    keyed by a fingerprint still pins a spec carrying those function
    objects, so the ids cannot be recycled while the entry lives.
    """
    import dataclasses

    import numpy as np

    cached = getattr(spec, "_cimba_fingerprint", None)
    if cached is not None:
        return cached

    def ref_key(r):
        # component refs are flat dataclasses of scalars/strings plus
        # the occasional callable (condition predicates) or tuple
        out = []
        for f in dataclasses.fields(r):
            v = getattr(r, f.name)
            if callable(v):
                out.append(("fn", id(v)))
            elif isinstance(v, (list, tuple)):
                out.append(tuple(v))
            else:
                out.append(v)
        return tuple(out)

    fp = (
        spec.name,
        tuple(id(b) for b in spec.blocks),
        np.asarray(spec.proc_entry).tobytes(),
        np.asarray(spec.proc_prio).tobytes(),
        np.asarray(spec.proc_start).tobytes(),
        tuple(spec.proc_names),
        tuple(ref_key(q) for q in spec.queues),
        tuple(ref_key(r) for r in spec.resources),
        tuple(ref_key(p) for p in spec.pools),
        tuple(ref_key(b) for b in spec.buffers),
        tuple(ref_key(q) for q in spec.pqueues),
        tuple(ref_key(c) for c in spec.conditions),
        spec.n_guards, spec.guard_cap, spec.event_cap,
        spec.queue_cap_max, spec.pqueue_cap_max,
        spec.n_flocals, spec.n_ilocals, spec.max_chain,
        None if spec.user_init is None else id(spec.user_init),
        tuple(id(h) for h in spec.user_handlers),
        tuple(spec.boundary_pcs),
    )
    try:
        object.__setattr__(spec, "_cimba_fingerprint", fp)
    except (AttributeError, TypeError):
        pass  # slotted/frozen spec: recompute per call (cheap)
    return fp


# cimba-check: content-path
def program_class_key(spec, with_metrics: bool, *, mesh, pack) -> tuple:
    """The Tier-A **compatibility class**: everything a compiled chunk
    program bakes in EXCEPT ``chunk_steps`` — the spec's structural
    fingerprint, the dtype profile, the ``obs.metrics``/``obs.trace``
    flags, the event-set layout, the resolved ``pack`` arm, and the
    mesh — with the trace-time globals resolved NOW so a flag flip
    between calls misses the cache rather than replaying the stale arm.

    Seed, horizon (``t_end``), params values, R, and priority are all
    per-lane DATA on this path (``runner.experiment._init_program``'s
    seed/horizon columns), so they join neither this class nor the
    program key: requests differing only in them share one wave of one
    compiled program.  ``chunk_steps`` is excluded because chunking is
    trajectory-invariant (chunked == monolithic bitwise, docs/12): two
    requests with different chunk budgets may share a wave — the wave
    simply runs at its lead's chunk size — but each distinct
    ``chunk_steps`` actually dispatched still compiles its own program
    (:func:`program_key` appends it)."""
    from cimba_tpu import config as _config
    from cimba_tpu.obs import trace as _trace

    return (
        spec_fingerprint(spec),
        _config.active_profile(),
        bool(with_metrics),
        pack if pack is not None else _config.xla_pack_enabled(),
        _trace.enabled(),
        _config.eventset_hier_enabled(),
        _config.eventset_block(),
        mesh,
    )


# cimba-check: content-path
def program_key(
    spec, with_metrics: bool, *, mesh, pack, chunk_steps: int,
) -> tuple:
    """The full key of one compiled ``(init, chunk)`` program pair:
    the compatibility class plus the chunk budget the program bakes in.
    Any component silently replaying stale would return a DIFFERENT
    model's trajectories with no error — which is why the trace-time
    globals resolve into the class at key-build time."""
    return program_class_key(
        spec, with_metrics, mesh=mesh, pack=pack,
    ) + (chunk_steps,)


def get_programs(
    programs: MutableMapping,
    spec,
    *,
    mesh,
    pack,
    chunk_steps: int,
    with_metrics: bool,
    audit: bool = False,
):
    """The stream runner's ``get_programs``, shared with the service:
    one compiled ``(init, chunk)`` pair per :func:`program_key` point
    (jit re-specializes per wave shape internally, so full waves share
    one compile).  The chunk program is built with ``t_end=None``: the
    horizon is the per-lane ``t_stop`` column the init program plants
    (see ``Sim.t_stop``).  Returns ``(init_j, chunk_j)``.

    A memory miss gets a SECOND-CHANCE lookup in the persistent AOT
    program store (docs/15_program_store.md) before compiling: when the
    cache (or ``CIMBA_PROGRAM_STORE``) names a store holding a valid
    artifact for this program key, the entry hydrates deserialized
    executables instead of tracing and invoking XLA — the
    zero-cold-start path.  Every store failure mode (corrupt artifact,
    version/backend drift, unstable fingerprint, plain bug) degrades to
    the compile below, never to a wrong program.

    ``audit=True`` selects the determinism-audit chunk program (a
    third digest output per chunk, docs/18_audit.md): its key gets a
    distinct suffix — ``audit=False`` keys are byte-identical to the
    historical ones — and store hydration is skipped, because stored
    artifacts are always the unaudited two-output program."""
    from cimba_tpu.serve import store as _pstore

    _pstore.maybe_enable_persistent_cache()
    key = program_key(
        spec, with_metrics, mesh=mesh, pack=pack, chunk_steps=chunk_steps,
    )
    if audit:
        key = key + ("audit",)

    def build():
        import warnings as _warnings

        from cimba_tpu.runner import experiment as ex

        st = getattr(programs, "store", None)
        if st is None and not isinstance(programs, ProgramCache):
            st = _pstore.default_store()
        if audit:
            st = None  # store artifacts are unaudited programs
        if st is not None:
            try:
                hyd = st.hydrate(
                    spec, mesh=mesh, pack=pack, chunk_steps=chunk_steps,
                    with_metrics=with_metrics,
                )
            except Exception as e:  # a store bug must never block serving
                _warnings.warn(
                    f"program store lookup failed ({type(e).__name__}: "
                    f"{e}); compiling instead",
                    _pstore.StoreInvalidationWarning,
                )
                hyd = None
            if hyd is not None:
                return (hyd[0], hyd[1], spec)
        return (
            ex._init_program(spec, mesh),
            ex._chunk_program(
                spec, None, pack, chunk_steps, mesh, audit=audit
            ),
            spec,  # pins the fingerprint's function ids while cached
        )

    return _get_or_create(programs, key, build)[:2]


def get_refill_programs(
    programs: MutableMapping,
    spec,
    *,
    mesh,
    pack,
    with_metrics: bool,
):
    """The refill plane's compiled pair for one compatibility class:
    ``(refill_j, live_j)`` — the donated lane-splice program and the
    per-lane liveness readback (docs/22_refill.md).  Keyed by the SAME
    compatibility class the chunk program keys by (the Sim pytree a
    splice must reproduce is the class's — profile, metrics/trace
    leaves, event-set layout), so a refill can never splice rows laid
    out for a different program.  No store hydration: both programs
    are small host compiles (the chunk program dominates cold start),
    though ``CIMBA_PROGRAM_STORE`` still softens them to disk hits via
    jax's persistent compilation cache."""
    from cimba_tpu.serve import store as _pstore

    _pstore.maybe_enable_persistent_cache()
    key = ("refill",) + program_class_key(
        spec, with_metrics, mesh=mesh, pack=pack,
    )

    def build():
        from cimba_tpu.runner import experiment as ex

        return (
            ex._refill_program(spec, mesh),
            ex._live_program(spec, mesh),
            spec,  # pins the fingerprint's function ids while cached
        )

    return _get_or_create(programs, key, build)[:2]


# -- the fusion rung (docs/26_wave_fusion.md) --------------------------------
#
# The class ladder grows a SECOND rung above the exact compatibility
# class: a **fusion class** groups compatible-shape specs
# (core/fuse.fusion_shape_key + a shared Sim-structure signature) so
# cross-spec requests can share ONE compiled superprogram.  The merged
# spec is a real ModelSpec, so its chunk program, store entries and
# program-size probes ride the existing machinery unchanged; only init
# and refill need fused twins (a per-lane spec-id switch).


def fusion_order_key(spec) -> str:
    """Canonical member ordering for fused bundles: members sort by the
    VALUE-based ``stable_spec_fingerprint`` digest (docs/15), so the
    same member SET always builds the same merged table — and hence the
    same compiled superprogram — regardless of arrival order.  A spec
    that resists value fingerprinting falls back to an in-process key
    (name + id): deterministic within the process, which is all the
    ordering needs (programs cache per process)."""
    cached = getattr(spec, "_cimba_fusion_order", None)
    if cached is not None:
        return cached
    import hashlib

    from cimba_tpu.serve import store as _pstore

    try:
        key = "s:" + hashlib.sha256(
            repr(_pstore.stable_spec_fingerprint(spec)).encode("utf-8")
        ).hexdigest()
    except Exception:
        key = f"u:{spec.name}:{id(spec):x}"
    try:
        object.__setattr__(spec, "_cimba_fusion_order", key)
    except (AttributeError, TypeError):
        pass
    return key


def sim_structure_sig(
    programs: MutableMapping,
    spec,
    params,
    n_replications: int,
    with_metrics: bool,
    *,
    mesh,
    pack,
) -> tuple:
    """The full Sim STRUCTURE signature of one lane of this request —
    treedef plus per-leaf (lane-row shape, dtype) from ``eval_shape``
    over the init program (no device work).  The fusion class embeds it
    so two specs only ever share a fused wave when their lanes' pytrees
    are identical — a structure mismatch lands in a different fusion
    class instead of exploding inside ``lax.switch`` at trace time
    (docs/26_wave_fusion.md).  Memoized beside the programs it guards."""
    key = ("sim_sig",) + program_class_key(
        spec, with_metrics, mesh=mesh, pack=pack,
    ) + (_params_sig(params, n_replications),)

    def build():
        import jax
        import jax.numpy as jnp

        from cimba_tpu.core.loop import init_sim
        from cimba_tpu.runner import experiment as ex

        def one_lane():
            reps = jnp.arange(0, 1)
            seeds = ex._seed_column(0, 1)
            ts = ex._horizon_column(None, 1)
            pw = ex._slice_params(params, n_replications, 0, 1)
            return jax.vmap(
                lambda r, s, t, q: init_sim(spec, s, r, q, t_stop=t)
            )(reps, seeds, ts, pw)

        sim = jax.eval_shape(one_lane)
        leaves, treedef = jax.tree.flatten(sim)
        sig = (
            str(treedef),
            tuple(
                (tuple(l.shape[1:]), str(l.dtype)) for l in leaves
            ),
        )
        return (sig, spec)  # pins the fingerprint's ids while cached

    return _get_or_create(programs, key, build)[0]


def _params_sig(params, n_replications: int) -> tuple:
    """The params-row tree signature (treedef + per-lane leaf shapes and
    dtypes) — the same signature ``request_class_key`` embeds, shared
    here so the fusion class keys it identically."""
    import jax

    from cimba_tpu.runner import experiment as ex

    row = jax.eval_shape(
        lambda: ex._slice_params(params, n_replications, 0, 1)
    )
    leaves, treedef = jax.tree.flatten(row)
    return (
        str(treedef),
        tuple((tuple(l.shape[1:]), str(l.dtype)) for l in leaves),
    )


def get_fused(programs: MutableMapping, specs) -> "object":
    """The cached fused bundle (:class:`cimba_tpu.core.fuse.FusedSpec`)
    for an ORDERED member tuple.  Caching the bundle — not just its
    programs — is load-bearing: :func:`cimba_tpu.core.fuse.fuse_specs`
    creates fresh rebasing wrappers per call, so an uncached re-fuse
    would mint a fresh merged fingerprint and recompile everything.
    One bundle per member tuple makes the merged spec's fingerprint
    stable for the life of the cache entry (which pins every member)."""
    from cimba_tpu.core import fuse as _fuse

    specs = tuple(specs)
    key = ("fuse_bundle",) + tuple(spec_fingerprint(s) for s in specs)

    def build():
        return (_fuse.fuse_specs(specs),)

    return _get_or_create(programs, key, build)[0]


def get_fused_wave_programs(
    programs: MutableMapping,
    fused,
    *,
    mesh,
    pack,
    chunk_steps: int,
    with_metrics: bool,
):
    """The fused wave's compiled pair: ``(finit_j, chunk_j)``.  The
    chunk program is the ORDINARY :func:`get_programs` entry for the
    merged superspec (block dispatch is already a per-lane pc switch,
    so the merged table needs no special chunk program — and the store,
    warmers and program-size probes all see a normal spec); only init
    is fused (``runner.experiment._fused_init_program`` — the per-lane
    spec-id switch, docs/26_wave_fusion.md)."""
    init_key = ("fused_init",) + program_class_key(
        fused.spec, with_metrics, mesh=mesh, pack=pack,
    )

    def build():
        from cimba_tpu.runner import experiment as ex

        return (
            ex._fused_init_program(fused, mesh),
            fused,  # pins members + merged fingerprints while cached
        )

    finit_j = _get_or_create(programs, init_key, build)[0]
    _, chunk_j = get_programs(
        programs, fused.spec, mesh=mesh, pack=pack,
        chunk_steps=chunk_steps, with_metrics=with_metrics,
    )
    return finit_j, chunk_j


def get_fused_refill_programs(
    programs: MutableMapping,
    fused,
    *,
    mesh,
    pack,
    with_metrics: bool,
):
    """The fused refill plane's compiled pair: ``(frefill_j, live_j)``
    — the spec-id-switched lane splice and the per-lane liveness
    readback.  Liveness is member-independent (``make_cond`` reads
    horizon/done/err, never the block table), so the merged spec's
    ordinary live program serves every member's lanes."""
    key = ("fused_refill",) + program_class_key(
        fused.spec, with_metrics, mesh=mesh, pack=pack,
    )

    def build():
        from cimba_tpu.runner import experiment as ex

        return (
            ex._fused_refill_program(fused, mesh),
            ex._live_program(fused.spec, mesh),
            fused,  # pins members + merged fingerprints while cached
        )

    return _get_or_create(programs, key, build)[:2]


#: conservative working-set multiplier when no measured program
#: footprint is available: the chunk program donates its carry, so the
#: steady state holds roughly input + output + XLA temps — 3x the lane
#: buffers bounds that from above without a compile
_FOOTPRINT_SAFETY = 3


def wave_footprint_bytes(
    programs: MutableMapping,
    spec,
    *,
    mesh,
    pack,
    chunk_steps: int,
    with_metrics: bool,
    lanes: int,
    params,
    n_replications: int,
) -> int:
    """Estimated device bytes ONE wave of ``lanes`` lanes holds while
    live — the memory-aware admission cost of the device scheduler
    (docs/24_device_scheduler.md): the Sim pytree's lane buffers (from
    ``jax.eval_shape`` over the init program — no device work) plus
    the chunk program's own working set, resolved down a ladder:

    1. a store-persisted ``footprint_bytes`` on the hydrated chunk
       program (measured by ``save_programs`` at AOT-compile time —
       no re-lowering, the PR 17 manifest satellite);
    2. ``chunk_j.lower(aval).compile().memory_analysis()`` where the
       backend implements it (one AOT compile per (class, shape)
       point, memoized here like any program);
    3. a conservative estimate (``_FOOTPRINT_SAFETY`` x the lane
       buffers) when neither is available.

    Memoized in ``programs`` under a ``("footprint", ...)`` key beside
    the programs it describes, so steady-state admission never
    recomputes (and never compiles) anything."""
    import jax

    from cimba_tpu.runner import experiment as ex

    row_aval = jax.eval_shape(
        lambda: ex._slice_params(params, n_replications, 0, 1)
    )
    psig = (
        jax.tree.structure(row_aval),
        tuple(
            (tuple(l.shape[1:]), str(l.dtype))
            for l in jax.tree.leaves(row_aval)
        ),
    )
    key = ("footprint",) + program_key(
        spec, with_metrics, mesh=mesh, pack=pack,
        chunk_steps=chunk_steps,
    ) + (int(lanes), psig)

    def build():
        import jax.numpy as jnp
        import numpy as np

        init_j, chunk_j = get_programs(
            programs, spec, mesh=mesh, pack=pack,
            chunk_steps=chunk_steps, with_metrics=with_metrics,
        )
        L = int(lanes)
        pw = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (L,) + x.shape[1:]),
            ex._slice_params(params, n_replications, 0, 1),
        )
        sims_aval = jax.eval_shape(
            init_j, jnp.arange(L), ex._seed_column(0, L),
            ex._horizon_column(None, L), pw,
        )
        buf = sum(
            int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
            for l in jax.tree.leaves(sims_aval)
        )
        prog = None
        # rung 1: the store-measured footprint riding the hydrated
        # chunk program (no lowering, no compile)
        fp_for = getattr(chunk_j, "footprint_for", None)
        if fp_for is not None:
            prog = fp_for(sims_aval)
        elif hasattr(chunk_j, "lower"):
            # rung 2: AOT memory_analysis — unimplemented on some
            # backends (and the whole rung is best-effort: admission
            # must never fail because a compiler API moved)
            try:
                mem = chunk_j.lower(sims_aval).compile() \
                    .memory_analysis()
                prog = _memory_analysis_bytes(mem)
            except Exception:
                prog = None
        if prog is None or prog <= 0:
            # rung 3: conservative estimate — the extra copies bound
            # donated-carry temps from above
            prog = (_FOOTPRINT_SAFETY - 1) * buf
        return int(buf + prog)

    return _get_or_create(programs, key, build)


def _memory_analysis_bytes(mem) -> "int | None":
    """Sum the working-set fields a PjRt ``memory_analysis()`` object
    exposes (field names vary by backend/version — absent ones count
    0; a backend returning None yields None)."""
    if mem is None:
        return None
    total = 0
    for f in ("temp_size_in_bytes", "output_size_in_bytes",
              "argument_size_in_bytes"):
        try:
            total += int(getattr(mem, f, 0) or 0)
        except (TypeError, ValueError):
            pass
    return total if total > 0 else None


def get_fold(programs: MutableMapping, with_metrics: bool, summary_path):
    """The jitted wave-fold program shared by the stream runner and the
    service's per-request accumulators: merge the wave's pooled Pébay
    summary, failure count, event total, and (when enabled) pooled
    metrics registry into the accumulator tuple.  Keyed by the metrics
    flag and ``summary_path`` identity — a different statistic is a
    different program.  Folds have no explicit store artifact, but
    ``CIMBA_PROGRAM_STORE`` still softens their recompile to a disk
    hit via jax's persistent compilation cache (mechanism (a),
    docs/15_program_store.md)."""
    from cimba_tpu.serve import store as _pstore

    _pstore.maybe_enable_persistent_cache()
    key = ("fold", with_metrics, summary_path)

    def build():
        return _fold_program(with_metrics, summary_path)

    return _get_or_create(programs, key, build)


def _fold_program(with_metrics: bool, summary_path):
    """Build the jitted wave-fold program (the body of
    :func:`get_fold`, factored out so the store can AOT-compile it for
    fold artifacts and ``warm(manifest=...)`` can wrap it in a
    hydration shim)."""
    import jax
    import jax.numpy as jnp

    from cimba_tpu.obs import metrics as _metrics
    from cimba_tpu.stats import summary as sm

    def fold(acc, sims):
        if (sims.metrics is None) == with_metrics:
            raise RuntimeError(
                "run_experiment_stream: obs.metrics was "
                f"{'enabled' if with_metrics else 'disabled'} when "
                "the stream started but flipped mid-stream — the "
                "flag binds for the whole stream"
            )
        pooled = sm.merge_tree(summary_path(sims))
        out = (
            sm.merge(acc[0], pooled),
            acc[1] + jnp.sum((sims.err != 0).astype(jnp.int64)),
            acc[2] + jnp.sum(sims.n_events.astype(jnp.int64)),
        )
        if with_metrics:
            out = out + (
                _metrics.merge(acc[3], _metrics.pool(sims.metrics)),
            )
        return out

    # no donation on the accumulator: its leaves are scalars
    # (aliasing buys nothing) and sm.empty() aliases one zero buffer
    # across moments, which XLA's donation path rejects as a
    # double-donate
    return jax.jit(fold)


def get_gather(programs: MutableMapping):
    """The jitted lane-gather the fold sites slice waves with: ONE
    compiled program per (Sim structure, index shape) instead of a
    per-leaf eager dispatch chain — a wave Sim is ~40 leaves, and 40
    eager ``x[idx]`` dispatches cost ~1 ms each on a loaded host, so
    the gather (not the fold, which is already jitted) was the serve
    dispatcher's per-retirement wall.  Pure integer indexing: the
    gathered leaves are bitwise the eager slice, so every fold
    downstream stays bitwise its direct call's."""
    def build():
        import jax

        return jax.jit(
            lambda sims, idx: jax.tree.map(lambda x: x[idx], sims)
        )

    return _get_or_create(programs, ("gather",), build)


def stream_acc(spec, with_metrics: bool):
    """A zeroed accumulator tuple for :func:`get_fold`'s program:
    ``(Summary, n_failed i64, total_events i64[, Metrics])``."""
    import jax.numpy as jnp

    from cimba_tpu.core import loop as _cl
    from cimba_tpu.obs import metrics as _metrics
    from cimba_tpu.stats import summary as sm

    acc = (
        sm.empty(),
        jnp.zeros((), jnp.int64),
        jnp.zeros((), jnp.int64),
    )
    if with_metrics:
        acc = acc + (
            _metrics.create(
                _cl.N_KINDS + len(spec.user_handlers), len(spec.queues)
            ),
        )
    return acc


def preflight_summary_path(
    programs: MutableMapping,
    spec,
    init_j,
    summary_path,
    params,
    n_total: int,
    n_first: int,
    with_metrics: bool,
) -> None:
    """Trace ``summary_path`` over the first wave's ABSTRACT sims
    (``eval_shape`` of init∘path — milliseconds, tracers not structs so
    compute-style paths work) so a path that doesn't exist on this
    model's Sim fails here with the knob named, not as an opaque
    KeyError from inside the fold after a full wave of compute.  Cached
    by the spec's structural fingerprint (twin specs share the check)
    so a warmed cache skips the re-trace inside bench's timed region
    (the entry pins spec, keeping the fingerprint's ids valid)."""
    key = (
        "preflight", spec_fingerprint(spec), summary_path, with_metrics,
    )
    if key in programs:
        return

    def check():
        import jax
        import jax.numpy as jnp

        from cimba_tpu.runner import experiment as ex

        try:
            jax.eval_shape(
                lambda r, s, t, p: summary_path(init_j(r, s, t, p)),
                jnp.arange(n_first),
                ex._seed_column(0, n_first),
                ex._horizon_column(None, n_first),
                ex._slice_params(params, n_total, 0, n_first),
            )
        except Exception as e:
            raise ValueError(
                "run_experiment_stream: summary_path failed on this "
                f"model's Sim structure ({e!r}) — pass summary_path= "
                "pointing at a statistic this model records"
            ) from e
        return spec  # pins the fingerprint's function ids while cached

    _get_or_create(programs, key, check)


def warm(
    cache: MutableMapping,
    spec,
    params,
    wave_size: int,
    *,
    manifest=None,
    **stream_kwargs,
):
    """Warm-up precompile, two modes.

    Default (``manifest=None``): run ONE full wave through the stream
    runner against ``cache``, so a service built over the same cache
    (and a structurally-identical spec / settings — seed and horizon
    don't matter, they are per-lane data) serves its first real
    request from already-compiled programs.  Returns the warm-up
    wave's ``StreamResult`` (callers usually discard it).

    AOT mode (``manifest=`` a store root path or
    :class:`~cimba_tpu.serve.store.ProgramStore`): no dummy wave — the
    (spec, settings) program key hydrates from the store's serialized
    executables straight into ``cache`` (docs/15_program_store.md).  A
    missing or invalidated entry raises ``LookupError`` LOUDLY — a
    fleet rollout must find out at warm time, not discover a
    minutes-long compile on its first request — and the store's
    counters say why (corrupt / version drift / plain miss).  Returns
    the :class:`~cimba_tpu.serve.store.ProgramStore`.

    The wave-FOLD program hydrates too when the store carries a fold
    artifact for ``summary_path`` (saved by default —
    ``ProgramStore.save_programs(summary_paths=...)``); with no fold
    artifact and ``params`` given, the fold is instead built on THIS
    thread with one fold application over an init'd (never
    chunk-driven) wave of ``wave_size`` lanes — deferring it to the
    service's dispatcher thread costs several times the main-thread
    build (measured ~4.6x on the CPU window, BENCH_NOTES round 8).
    Pass ``params=None`` to hydrate strictly from artifacts."""
    from cimba_tpu.runner import experiment as ex

    if manifest is None:
        res = ex.run_experiment_stream(
            spec, params, wave_size, wave_size=wave_size,
            program_cache=cache, **stream_kwargs,
        )
        # the serve fold sites slice waves through the jitted lane
        # gather — a once-per-cache program the direct stream path
        # never builds; prime it so the warmed service's first
        # retirement is a cache hit, not a compile
        get_gather(cache)
        return res

    from cimba_tpu.obs import metrics as _metrics
    from cimba_tpu.serve import store as _pstore

    st = (
        manifest if isinstance(manifest, _pstore.ProgramStore)
        else _pstore.get_store(str(manifest))
    )
    if isinstance(cache, ProgramCache) and cache._store is None:
        # bind the cache to THIS store so later lookups (and the
        # service's stats) hit the same instance/counters the warm did
        cache._store = st
    mesh = stream_kwargs.pop("mesh", None)
    pack = stream_kwargs.pop("pack", None)
    chunk_steps = stream_kwargs.pop("chunk_steps", None)
    summary_path = stream_kwargs.pop("summary_path", None)
    n_replications = stream_kwargs.pop("n_replications", None)
    if stream_kwargs:
        raise TypeError(
            "serve.warm(manifest=...): unsupported kwargs in AOT mode: "
            f"{sorted(stream_kwargs)} (only mesh/pack/chunk_steps/"
            "summary_path/n_replications select a program)"
        )
    if chunk_steps is None or pack is None:
        # tuned-schedule resolution at program-build time
        # (docs/21_autotune.md): the hydrated program key must be the
        # one the service will actually dispatch, and the service
        # resolves at the REQUEST's workload bucket — pass
        # ``n_replications=`` when requests will carry a different R
        # than ``wave_size`` (the bucket is pow2(R), not pow2(wave)),
        # or explicit kwargs to pin the knobs outright
        from cimba_tpu.tune import registry as _tune_reg

        rs = _tune_reg.resolve_entry(
            spec, int(n_replications or wave_size or 0), pack=pack,
            chunk_steps=chunk_steps, store=st,
        )
        chunk_steps, pack = rs.chunk_steps, rs.pack
    if summary_path is None:
        summary_path = ex.default_summary_path
    with_metrics = _metrics.enabled()
    key = program_key(
        spec, with_metrics, mesh=mesh, pack=pack, chunk_steps=chunk_steps,
    )
    folds: dict = {}
    if key not in cache:
        hyd = st.hydrate(
            spec, mesh=mesh, pack=pack, chunk_steps=chunk_steps,
            with_metrics=with_metrics,
        )
        if hyd is None:
            raise LookupError(
                f"serve.warm(manifest=...): the store at {st.root} has "
                "no loadable artifact for this (spec, settings) "
                "program key — build one with tools/warm_store.py "
                f"(store stats: {st.stats()})"
            )
        # deserialize NOW, on the calling thread: lazy resolution would
        # land on the service's dispatcher thread, which pays ~4.6x
        hyd.init.resolve_all()
        hyd.chunk.resolve_all()
        cache[key] = (hyd.init, hyd.chunk, spec)
        folds = hyd.folds

    fold_key = ("fold", with_metrics, summary_path)
    if fold_key not in cache:
        try:
            pdig = _pstore.callable_digest(summary_path)
        except _pstore.UnstableStoreKey:
            pdig = None
        table = {
            shape: fn for (d, shape), fn in folds.items() if d == pdig
        }
        fold_j = _fold_program(with_metrics, summary_path)
        if table:
            for art in table.values():
                art.resolve()
            cache[fold_key] = _pstore.hydrated_fold(fold_j, table, st)
        elif params is not None and wave_size:
            # no fold artifact: build it HERE (main thread) with one
            # fold application over an init'd, never-driven wave
            cache[fold_key] = fold_j
            n = int(wave_size)
            init_fn = cache[key][0]
            sims0 = init_fn(
                ex.jnp.arange(n), ex._seed_column(0, n), None,
                ex._slice_params(params, n, 0, n),
            )
            fold_j(stream_acc(spec, with_metrics), sims0)
    get_gather(cache)
    return st
