"""Sequential stopping for sweeps: run each cell only until its
confidence interval is tight enough.

Raw events/second is only half of statistical throughput — a sweep
whose easy cells run as long as its hardest cell wastes most of its
replications.  The adaptive engine runs the grid in ROUNDS: after each
round every still-live cell's CI halfwidth (the shared
:func:`cimba_tpu.stats.summary.halfwidth` definition) is checked
against a target, converged cells stop receiving lanes, and the freed
lanes go to the cells still running.

Determinism contract (docs/16_sweeps.md): the replications of round
``r`` of cell ``c`` are ``(seed=round_seed(seed, c, r), rep=0..n)`` —
a pure function of the experiment seed and the (cell, round)
coordinates, independent of which OTHER cells are still live, of wave
packing, and of whether the round was dispatched directly or through a
:class:`~cimba_tpu.serve.service.Service`.  Re-running an adaptive
sweep therefore reproduces every cell's trajectory set (and its
summary, bitwise) even though the stopping pattern reshapes every
round's waves.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

_M64 = (1 << 64) - 1
#: golden-ratio increment (the same constant ``random.bits.initialize``
#: uses to separate replication streams under one seed)
_GOLDEN = 0x9E3779B97F4A7C15
_ROUND = 0xBF58476D1CE4E5B9  # splitmix64 multiplier — round separation


def _fmix64(h: int) -> int:
    """MurmurHash3 64-bit finalizer on host ints (the pure-python twin
    of ``random.bits.fmix64`` — scheduling must not touch the device)."""
    h &= _M64
    h ^= h >> 33
    h = (h * 0xFF51AFD7ED558CCD) & _M64
    h ^= h >> 33
    h = (h * 0xC4CEB9FE1A85EC53) & _M64
    h ^= h >> 33
    return h


def round_seed(seed: int, cell: int, round_: int = 0) -> int:
    """The u64 seed of (cell, round) under experiment ``seed`` — the
    deterministic schedule's only source of randomness identity.

    Two fmix64 passes keep distinct (cell, round) pairs on
    statistically independent Threefry keys even after ``init_sim``'s
    own ``seed + GOLDEN*rep`` per-lane derivation (independence here is
    statistical, not cryptographic — same contract as the reference's
    per-trial seed mix).  ``round_=0`` is also the FIXED-R seed of a
    cell: a fixed sweep is one round of the same schedule, so a cell's
    fixed-R result is bitwise a direct ``run_experiment_stream`` call
    at ``seed=round_seed(seed, c, 0)`` (the tier-1 engine pin)."""
    h = _fmix64((int(seed) + _GOLDEN * (int(cell) + 1)) & _M64)
    return _fmix64((h + _ROUND * (int(round_) + 1)) & _M64)


@functools.lru_cache(maxsize=None)
def _halfwidths_jit(confidence: float):
    """ONE jitted batched-halfwidth program per confidence level —
    jax.jit caches by function identity, so wrapping a fresh lambda
    per stopping round would retrace every round."""
    import jax

    from cimba_tpu.stats import summary as sm

    return jax.jit(jax.vmap(lambda s: sm.halfwidth(s, confidence)))


def replication_means(base_path=None):
    """A ``summary_path`` whose samples are REPLICATION MEANS: each
    lane's base summary collapses to the single sample ``mean(s)``, so
    the pooled cell summary is the classic batch-means estimator —
    ``n`` = replications, and :func:`~cimba_tpu.stats.summary.halfwidth`
    becomes the replication-level CI.

    Use this as ``run_sweep(..., summary_path=...)`` when the base
    statistic's within-replication samples are autocorrelated (queue
    sojourns at high utilization very much are): the default
    pooled-sample CI treats every sample as exchangeable and reads far
    too narrow there, while replication means are genuinely
    independent (counter-derived streams).  Each replication weighs
    equally regardless of its sample count — the standard batch-means
    trade.

    ``base_path=None`` wraps the runner's default (the model's
    ``wait`` summary).  Calls memoize on the base path's identity, so
    repeated calls return the SAME function object and the fold
    program / serve compatibility caches keyed on ``summary_path``
    identity keep hitting."""
    return _replication_means_cached(base_path)


@functools.lru_cache(maxsize=None)
def _replication_means_cached(base_path):
    import jax

    from cimba_tpu.stats import summary as sm

    def path(sims):
        from cimba_tpu.runner.experiment import default_summary_path

        base = base_path if base_path is not None else default_summary_path
        return jax.vmap(lambda s: sm.add(sm.empty(), sm.mean(s)))(
            base(sims)
        )

    path.__name__ = "replication_means(%s)" % getattr(
        base_path, "__name__", "default_summary_path"
    )
    return path


@dataclass(frozen=True)
class HalfwidthTarget:
    """Stop a cell when the CI halfwidth of its pooled mean beats a
    target (the ``stop=`` argument of :func:`cimba_tpu.sweep.run_sweep`).

    ``target`` is an absolute halfwidth, or — with ``relative=True`` —
    a fraction of the cell's |mean| (the usual "mean known to ±5%"
    framing; relative targets make a grid whose cells live on different
    scales converge to comparable precision).  ``confidence`` feeds the
    shared :func:`cimba_tpu.stats.summary.halfwidth` definition.
    ``min_reps`` guards the small-sample regime: a cell is never judged
    before it has that many replications, however narrow its early CI
    happens to look (2 lucky samples have a degenerate variance
    estimate, and the t-expansion is loosest exactly there).

    Coverage caveat: the CI is computed over whatever samples the
    sweep's ``summary_path`` pools.  The default path pools every
    WITHIN-replication sample as if exchangeable; when those are
    autocorrelated (queue waits at high utilization), the interval is
    optimistically narrow and the nominal confidence is not attained —
    ``min_reps`` delays judgment but does not fix the scaling.  For
    calibrated coverage on autocorrelated statistics, run the sweep
    with ``summary_path=sweep.replication_means()`` (batch-means CI:
    ``n`` = independent replications).
    """

    target: float
    relative: bool = False
    confidence: float = 0.95
    min_reps: int = 8

    def __post_init__(self):
        if not self.target > 0.0:
            raise ValueError(
                f"halfwidth target must be positive, got {self.target}"
            )
        if not 0.0 < self.confidence < 1.0:
            raise ValueError(
                f"confidence must be in (0, 1), got {self.confidence}"
            )

    def halfwidths(self, summaries):
        """Per-cell halfwidths of a batched Summary (device; one
        cached jitted program per confidence level)."""
        return _halfwidths_jit(self.confidence)(summaries)

    def met(self, summaries, n_reps):
        """np bool [C]: which cells' CIs beat the target.  ``n_reps``
        is the per-cell replication count (the ``min_reps`` guard
        counts replications, not pooled samples — a cell's summary may
        hold thousands of autocorrelated within-replication samples
        and still rest on too few independent replications)."""
        import numpy as np

        from cimba_tpu.stats import summary as sm

        hw = np.asarray(self.halfwidths(summaries), np.float64)
        if self.relative:
            bound = self.target * np.abs(
                np.asarray(sm.mean(summaries), np.float64)
            )
        else:
            bound = self.target
        return (hw <= bound) & (np.asarray(n_reps) >= self.min_reps)
