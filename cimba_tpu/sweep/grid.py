"""Declarative scenario grids: named axes over param-tree leaves.

The reference's only "sweep" is the hand-rolled M/G/1 experiment array
(``models/mg1.py::sweep_params``): 4 service CVs x 5 utilizations
unrolled into one row of parameters per replication.  A
:class:`SweepGrid` generalizes that pattern to any model: named axes
(each a sequence of values) span a Cartesian cell table, and a
``row`` function maps one cell's axis values to one row of the model's
param pytree.  :meth:`SweepGrid.rows` then stacks the rows into the
experiment-array layout the runner already understands — leading axis
``n_cells * reps_per_cell`` in cell-major order, delivered to lanes
through ``runner.experiment._slice_params`` so each replication's
trajectory is bitwise the monolithic broadcast (the M/G/1 sweep
regression, tests/test_stream.py).

The grid itself is pure host-side bookkeeping: no jax import at module
load, no device arrays until :meth:`rows` builds them.  The sweep
ENGINE (:mod:`cimba_tpu.sweep.engine`) consumes cells one at a time
via :meth:`cell_row` — it never materializes the full [R] array.
"""

from __future__ import annotations

from typing import Any, Callable, Mapping, Sequence


class SweepGrid:
    """A Cartesian scenario grid over a model's parameter tree.

    ``axes`` maps axis names to value sequences (insertion order is
    significant: the LAST axis varies fastest, matching the nested-loop
    order of the hand-rolled M/G/1 sweep).  ``row`` is called with one
    keyword argument per axis and returns the param pytree of ONE cell
    — scalar leaves (``np.float64(...)``/``np.int32(...)`` for exact
    dtype control); every cell must return the same tree structure and
    leaf dtypes.

        grid = SweepGrid(
            {"cv": (0.25, 0.5, 1.0, 2.0),
             "rho": (0.5, 0.6, 0.7, 0.8, 0.9)},
            lambda cv, rho: (np.float64(1.0 / rho), np.float64(1.0),
                             np.float64(cv), np.int32(n_objects)),
        )
    """

    def __init__(
        self,
        axes: Mapping[str, Sequence],
        row: Callable[..., Any],
        *,
        name: str = "sweep",
    ):
        if not axes:
            raise ValueError("SweepGrid needs at least one axis")
        self.axes = {str(k): tuple(v) for k, v in axes.items()}
        for k, vals in self.axes.items():
            if not vals:
                raise ValueError(f"axis {k!r} has no values")
        self.row = row
        self.name = name
        self._cells = None

    @property
    def n_cells(self) -> int:
        n = 1
        for vals in self.axes.values():
            n *= len(vals)
        return n

    def cells(self) -> tuple:
        """All cells as ``{axis: value}`` dicts, last axis fastest."""
        if self._cells is None:
            import itertools

            names = list(self.axes)
            self._cells = tuple(
                dict(zip(names, combo))
                for combo in itertools.product(*self.axes.values())
            )
        return self._cells

    def cell(self, i: int) -> dict:
        return dict(self.cells()[i])

    def cell_label(self, i: int) -> str:
        """``"cv=0.25,rho=0.5"`` — stable cell naming for serve labels,
        CSV rows, and bench reports."""
        return ",".join(f"{k}={v}" for k, v in self.cells()[i].items())

    def cell_row(self, i: int):
        """The param pytree of cell ``i`` (scalar leaves)."""
        return self.row(**self.cells()[i])

    def cell_rows(self) -> list:
        """Every cell's row, validated to share ONE tree structure —
        the check both :meth:`rows` and the sweep engine gate on (a
        ragged grid fails loudly with the offending cell named, not as
        a stack error deep in jax)."""
        import jax

        rows = [self.cell_row(i) for i in range(self.n_cells)]
        first = jax.tree.structure(rows[0])
        for i, r in enumerate(rows[1:], 1):
            if jax.tree.structure(r) != first:
                raise ValueError(
                    f"SweepGrid {self.name!r}: cell {i} "
                    f"({self.cell_label(i)}) returned a different param "
                    "tree structure than cell 0 — every cell must share "
                    "one structure"
                )
        return rows

    def rows(self, reps_per_cell: int):
        """The experiment array: every cell's row repeated
        ``reps_per_cell`` times along a new leading axis (cell-major —
        cell ``i``'s replications occupy rows
        ``[i*reps_per_cell, (i+1)*reps_per_cell)``), plus the matching
        ``cell_ids`` int array.  This is the fixed-R layout the
        monolithic runner (``run_experiment``) and the hand-rolled
        M/G/1 path consume; the sweep engine builds its waves per cell
        instead and never calls this."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        if reps_per_cell <= 0:
            raise ValueError(
                f"reps_per_cell must be positive, got {reps_per_cell}"
            )
        rows = self.cell_rows()
        params = jax.tree.map(
            lambda *xs: jnp.asarray(
                np.repeat(
                    np.stack([np.asarray(x) for x in xs], axis=0),
                    reps_per_cell,
                    axis=0,
                )
            ),
            *rows,
        )
        cell_ids = np.repeat(np.arange(self.n_cells), reps_per_cell)
        return params, cell_ids

    def __repr__(self):
        ax = ", ".join(f"{k}[{len(v)}]" for k, v in self.axes.items())
        return f"SweepGrid({self.name!r}: {ax} -> {self.n_cells} cells)"
