"""cimba_tpu.sweep — the many-scenario sweep engine (docs/16_sweeps.md).

A :class:`SweepGrid` declares named axes over a model's param-tree
leaves; :func:`run_sweep` fans the grid's cells x replications across
waves of the chunked stream program and folds **per-cell** pooled
Pébay summaries via slot-keyed applications of the shared fold
program (bitwise the direct per-cell stream calls).
``stop=HalfwidthTarget(...)``
turns raw events/second into statistical efficiency: each cell runs
only until its confidence interval beats the target (adaptive R,
deterministic seed schedule — reproducible bit-for-bit), and
``service=`` routes the same schedule through the serving layer so
sweeps pack into shared heterogeneous waves alongside live traffic.

    from cimba_tpu import sweep
    grid = mg1.sweep_grid(n_objects=10_000)
    res = sweep.run_sweep(
        spec, grid, reps_per_cell=32,
        stop=sweep.HalfwidthTarget(target=0.05, relative=True),
    )
    res.to_csv("mg1_sweep.csv")
"""

from cimba_tpu.sweep.adaptive import (
    HalfwidthTarget,
    replication_means,
    round_seed,
)
from cimba_tpu.sweep.engine import (
    SweepResult,
    run_fused_sweeps,
    run_sweep,
)
from cimba_tpu.sweep.grid import SweepGrid

__all__ = [
    "SweepGrid", "SweepResult", "HalfwidthTarget",
    "replication_means", "round_seed", "run_sweep",
    "run_fused_sweeps",
]
