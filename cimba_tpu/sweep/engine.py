"""The many-scenario sweep engine: cells x replications fanned across
waves of the chunked stream program, folded per cell.

``run_experiment_stream`` (PR 3) pools ONE statistic for one scenario;
a sweep wants one statistic PER CELL of a scenario grid.  This engine
drives the same compiled machinery — the shared ``(init, chunk)``
program pair from :mod:`cimba_tpu.serve.cache`, per-lane seed/horizon
columns (PR 5), donated chunked dispatch — but lays each wave out as a
sequence of per-cell SLOTS and folds it **slot-keyed**: each slot's
contiguous lanes slice off the wave (data movement only) and fold
through the ONE shared fold program into that cell's accumulator, so
the grid converges as per-cell pooled summaries (stacked to a batched
``Summary[C]`` for the stopping rule and the result) instead of the
stream runner's single grid-pooled scalar.

Why per-slot applications of the shared program rather than one fused
all-cells fold: the fixed-R contract below is BITWISE, and XLA only
preserves float semantics within one compiled program — a fused
segment-reduction program computing the same merges measurably drifts
from the direct path by 1 ulp in the high moments at model scale
(fusion/FMA contraction differ across program boundaries).  Program
identity with the direct call's fold is the whole proof.

Three dispatch modes, one schedule:

* **fixed-R** (``stop=None``): every cell runs ``reps_per_cell``
  replications.  Cell ``c``'s lanes are
  ``(seed=round_seed(seed, c, 0), rep=0..R)`` partitioned into
  ``cell_wave``-sized slots — exactly the wave partition of a direct
  ``run_experiment_stream(spec, row_c, R, wave_size=cell_wave,
  seed=round_seed(seed, c, 0))`` call, and the per-slot fold performs
  the same merge sequence from the same empty accumulator, so the
  engine's per-cell results are BITWISE the direct calls' (the tier-1
  pin, tests/test_sweep.py) while many cells share each physical wave.
* **adaptive-R** (``stop=HalfwidthTarget(...)``): rounds of
  ``reps_per_cell`` per live cell; after each round, cells whose CI
  halfwidth beats the target stop receiving lanes and the freed lanes
  go to the cells still running (``redistribute``).  The
  (cell, round) -> seed schedule is deterministic and
  packing-independent, so adaptive runs are reproducible bit-for-bit
  (docs/16_sweeps.md).
* **serve-backed** (``service=``): each (cell, round) submits as a
  :class:`~cimba_tpu.serve.service.Request` carrying its own per-lane
  seed and horizon, so sweep traffic packs into shared heterogeneous
  waves alongside live requests (PR 5 compatibility classes — same
  spec + scalar param rows means ONE class, no new program keys) and
  the per-cell results are bitwise the direct mode's fixed-R results.

Waves that cannot fill (``pad_waves=True``, or a mesh's device
quantum) pad with the bitwise-inert ``t_stop=-inf`` lanes of
docs/14_wave_packing.md; pad lanes sit past the live segment and never
join a fold.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import numpy as np

from cimba_tpu.sweep.adaptive import HalfwidthTarget, round_seed
from cimba_tpu.sweep.grid import SweepGrid

__all__ = ["SweepResult", "run_sweep", "run_fused_sweeps"]


@dataclass
class SweepResult:
    """Per-cell pooled statistics of one sweep run.

    ``summaries`` is a batched :class:`~cimba_tpu.stats.summary.Summary`
    with leading axis ``n_cells`` (device); the count arrays are host
    numpy.  ``stop_round[c]`` is the 0-based round after which cell
    ``c`` met the stopping target (-1: never — fixed-R runs, or cells
    still unconverged at ``max_rounds``); ``met`` is None for fixed-R
    runs.  ``occupancy`` carries the wave/lane accounting (live vs
    padded lanes — the obs-style efficiency counters; serve-backed runs
    report the service's counter deltas instead)."""

    grid: SweepGrid
    summaries: Any
    n_reps: np.ndarray
    n_failed: np.ndarray
    total_events: np.ndarray
    stop_round: np.ndarray
    halfwidth: np.ndarray
    met: Optional[np.ndarray]
    n_rounds: int
    seed: int
    confidence: float
    wall_s: float
    occupancy: dict = field(default_factory=dict)
    metrics: Any = None
    #: run card (docs/18_audit.md) when the sweep ran with ``audit=``:
    #: per-cell result digests + the deterministic seed schedule
    audit: Any = None

    @property
    def n_cells(self) -> int:
        return self.grid.n_cells

    def cell_summary(self, i: int):
        """Cell ``i``'s pooled Summary (scalar leaves, device)."""
        import jax

        return jax.tree.map(lambda x: x[i], self.summaries)

    def rows(self) -> list:
        """One dict per cell: axis values + pooled statistics — the
        dataset export (feed to csv/pandas/plotting)."""
        from cimba_tpu.stats import summary as sm

        s = self.summaries
        cols = {
            "n": s.n, "mean": sm.mean(s), "stddev": sm.stddev(s),
            "min": s.mn, "max": s.mx,
        }
        cols = {k: np.asarray(v, np.float64) for k, v in cols.items()}
        axes = set(self.grid.axes)

        def key(k):
            # an axis named like a statistic keeps its name; the
            # statistic column gets a stat_ prefix instead of silently
            # overwriting the cell coordinate
            return f"stat_{k}" if k in axes else k

        out = []
        for i, cell in enumerate(self.grid.cells()):
            row = dict(cell)
            row[key("reps")] = int(self.n_reps[i])
            row[key("n")] = float(cols["n"][i])
            row[key("mean")] = float(cols["mean"][i])
            row[key("stddev")] = float(cols["stddev"][i])
            row[key("halfwidth")] = float(self.halfwidth[i])
            row[key("min")] = float(cols["min"][i])
            row[key("max")] = float(cols["max"][i])
            row[key("n_failed")] = int(self.n_failed[i])
            row[key("total_events")] = int(self.total_events[i])
            row[key("stop_round")] = int(self.stop_round[i])
            if self.met is not None:
                row[key("met")] = bool(self.met[i])
            out.append(row)
        return out

    def to_csv(self, path) -> None:
        """Write :meth:`rows` as CSV (``path``: filename, Path, or
        file-like)."""
        import csv
        import os

        rows = self.rows()
        own = isinstance(path, (str, os.PathLike))
        f = open(path, "w", newline="") if own else path
        try:
            w = csv.DictWriter(f, fieldnames=list(rows[0]))
            w.writeheader()
            w.writerows(rows)
        finally:
            if own:
                f.close()


def _stack_summaries(accs):
    """The batched per-cell ``Summary[C]`` view of the per-cell
    accumulators — what the stopping rule vectorizes over and what
    :class:`SweepResult` carries.  Pure data movement (stack), so the
    per-cell scalars' bits pass through untouched."""
    import jax
    import jax.numpy as jnp

    return jax.tree.map(lambda *xs: jnp.stack(xs), *[a[0] for a in accs])


def _serve_merge(acc, summary, n_failed, total_events, metrics=None):
    """Merge one served (cell, round) StreamResult into that cell's
    accumulator — ``merge(empty, s)`` is exact, so a fixed-R serve run
    delivers each cell BITWISE as the service returned it (which is
    itself bitwise the direct stream call, the PR 4 contract)."""
    from cimba_tpu.obs import metrics as _metrics
    from cimba_tpu.stats import summary as sm

    out = (
        sm.merge(acc[0], summary),
        acc[1] + n_failed,
        acc[2] + total_events,
    )
    if metrics is not None:
        out = out + (_metrics.merge(acc[3], metrics),)
    return out


def _wave_shape(total: int, unit: int, pad_waves: bool, max_wave: int):
    """Lanes one physical wave dispatches at: always a multiple of the
    mesh device count; with ``pad_waves`` additionally quantized to the
    next power-of-two multiple (capped at ``max_wave``) so mixed rounds
    cycle a handful of compiled wave shapes — serve's pad-and-mask
    policy (docs/14_wave_packing.md)."""
    if total <= 0:
        return total
    up = total if total % unit == 0 else total + (unit - total % unit)
    if not pad_waves:
        return up
    q = unit
    while q < total:
        q *= 2
    q = min(q, max_wave)
    if q < up or q % unit:
        return up
    return q


def run_sweep(
    spec,
    grid: SweepGrid,
    *,
    reps_per_cell: int,
    stop: Optional[HalfwidthTarget] = None,
    max_rounds: int = 32,
    seed: int = 0,
    cell_wave: Optional[int] = None,
    max_wave: int = 4096,
    t_end: Optional[float] = None,
    pack: Optional[bool] = None,
    chunk_steps: Optional[int] = None,
    poll_every: int = 4,
    mesh=None,
    summary_path=None,
    pad_waves: bool = False,
    redistribute: bool = True,
    program_cache=None,
    service=None,
    serve_timeout: float = 600.0,
    on_round: Optional[Callable] = None,
    on_chunk: Optional[Callable] = None,
    telemetry=None,
    audit=None,
) -> SweepResult:
    """Run a scenario grid: ``reps_per_cell`` replications per cell
    (per ROUND when ``stop`` is given), folded into per-cell pooled
    summaries.

    Fixed-R mode (``stop=None``): one round; cell ``c``'s result is
    bitwise the direct ``run_experiment_stream`` call at
    ``seed=round_seed(seed, c, 0)``, ``wave_size=cell_wave`` (the
    engine merely packs many cells' slots into shared physical waves
    of up to ``max_wave`` lanes).

    Adaptive mode (``stop=HalfwidthTarget(...)``): up to ``max_rounds``
    rounds; after each round, cells whose CI halfwidth beats the
    target stop receiving lanes.  ``redistribute=True`` (default)
    grows the per-round replication count as cells drop out —
    ``reps_per_cell * n_cells / n_live``, capped at
    ``max(reps_per_cell, max_wave)`` lanes per cell per round — so the
    hardware stays busy while the hard cells converge.  The
    (cell, round) seed schedule is deterministic and independent of
    the stopping pattern: adaptive runs reproduce bit-for-bit.

    ``service=`` dispatches every (cell, round) as a serve Request
    instead (per-lane seeds/horizons — sweeps pack into shared
    heterogeneous waves with live traffic; ``mesh``/``program_cache``
    then belong to the service).  ``pad_waves`` quantizes direct-mode
    wave shapes with dead ``t_stop=-inf`` lanes (bitwise-inert; a mesh
    always pads to its device-count multiple).  ``on_round(round,
    n_live, reps_total)`` is the progress hook (bench.py's watchdog
    heartbeat ticks there).  ``telemetry`` attaches the host-side
    telemetry plane (docs/17_telemetry.md): per-round/per-chunk ticks
    (counter + liveness heartbeat) and — with spans enabled — one
    "sweep" trace whose per-round spans carry live-cell/replication
    counts; serve-backed sweeps additionally get the service's own
    request spans per (cell, round).  Host-side only: results are
    bitwise identical with or without it.

    ``chunk_steps=None`` / ``pack=None`` (the defaults) resolve
    through the tuned-schedule registry for the per-cell workload
    bucket at program-build time (docs/21_autotune.md) — explicit
    kwargs always win, ``CIMBA_TUNE=0`` restores the hand-frozen
    defaults bitwise, and the resolved block lands in the sweep run
    card's ``schedule`` section; serve-backed sweeps defer resolution
    to the service's own submit path.

    ``audit`` (docs/18_audit.md): ``None`` defers to ``CIMBA_AUDIT``;
    when enabled, the result carries a content-addressed run card in
    ``.audit`` with the full per-cell seed schedule (every
    ``round_seed(seed, cell, round)`` actually dispatched) and a
    bitwise result digest per cell — the citable form of the fixed-R
    "bitwise the direct calls" contract.  Host-side only (the
    dispatched programs are unchanged); per-chunk digest TRAILS are
    the stream runner's — sweep waves interleave many cells, so the
    sweep card pins cell results, not chunk boundaries."""
    import jax
    import jax.numpy as jnp

    from cimba_tpu.obs import audit as _obs_audit
    from cimba_tpu.obs import metrics as _metrics
    from cimba_tpu.runner import experiment as ex
    from cimba_tpu.serve import cache as _pcache

    C = grid.n_cells
    R0 = int(reps_per_cell)
    if R0 <= 0:
        raise ValueError(f"reps_per_cell must be positive, got {R0}")
    # tuned-schedule resolution at program-build time
    # (docs/21_autotune.md): the ARGUMENT knobs left unset resolve
    # against the program store for the per-cell workload bucket (a
    # cell runs as an R0-sized stream).  Explicit kwargs always win;
    # CIMBA_TUNE=0 restores the hand-frozen defaults bitwise.  Serve-
    # backed sweeps leave resolution to the service's own submit path.
    from cimba_tpu.tune import registry as _tune_reg

    if service is None:
        _store = (
            program_cache._store
            if hasattr(program_cache, "_store") else None
        )
        rs = _tune_reg.resolve_entry(
            spec, R0, pack=pack, chunk_steps=chunk_steps, store=_store,
        )
        pack, chunk_steps = rs.pack, rs.chunk_steps
        sched_block = rs.block()
    else:
        # serve-backed: chunk_steps=None flows into each Request and
        # the service resolves it at submit (one resolution authority)
        sched_block = None
    if stop is not None and max_rounds <= 0:
        raise ValueError(f"max_rounds must be positive, got {max_rounds}")
    cell_wave = R0 if cell_wave is None else int(cell_wave)
    if cell_wave <= 0:
        raise ValueError(f"cell_wave must be positive, got {cell_wave}")
    if cell_wave > max_wave:
        raise ValueError(
            f"cell_wave={cell_wave} exceeds max_wave={max_wave} — a "
            "slot could never fit one physical wave"
        )
    if service is not None and (mesh is not None or program_cache is not None):
        raise ValueError(
            "serve-backed sweeps dispatch through the service's own "
            "mesh and program cache — don't pass mesh=/program_cache="
        )
    unit = 1 if mesh is None else int(mesh.devices.size)
    if unit > 1 and (cell_wave % unit or max_wave % unit):
        raise ValueError(
            f"cell_wave={cell_wave} and max_wave={max_wave} must "
            f"divide evenly over {unit} devices"
        )

    rows = grid.cell_rows()
    if summary_path is None:
        summary_path = ex.default_summary_path
    with_metrics = _metrics.enabled()

    _, on_chunk = ex._tel_hooks(telemetry, "sweep", None, on_chunk)
    rec = telemetry.spans if telemetry is not None else None
    trace = root = None
    if rec is not None:
        trace = rec.new_trace()
        root = rec.start(
            trace, "sweep", grid=grid.name, n_cells=C,
            adaptive=stop is not None, serve_backed=service is not None,
        )

    t0 = time.perf_counter()
    occ = {
        "waves": 0, "lanes_live": 0, "lanes_padded": 0,
        "slots_by_cell": np.zeros(C, np.int64),
    }
    serve_stats0 = service.stats() if service is not None else None

    if service is None:
        programs = (
            program_cache if program_cache is not None
            else _pcache.ProgramCache()
        )
        init_j, chunk_j = _pcache.get_programs(
            programs, spec, mesh=mesh, pack=pack,
            chunk_steps=chunk_steps, with_metrics=with_metrics,
        )
        _pcache.preflight_summary_path(
            programs, spec, init_j, summary_path, rows[0],
            R0, min(cell_wave, R0), with_metrics,
        )
        # THE shared fold program — the same compiled object a direct
        # run_experiment_stream call folds through.  Program identity
        # is what makes per-cell results bitwise the direct calls':
        # XLA preserves float semantics within one compiled program,
        # not across two structurally different ones (a fused
        # all-cells-in-one-program fold measurably drifts by 1 ulp in
        # the high moments at model scale)
        fold_j = _pcache.get_fold(programs, with_metrics, summary_path)
    else:
        programs = service.cache

    # per-cell accumulators, every one starting from the same zeros a
    # direct stream call starts from (immutable — sharing is safe)
    acc0 = _pcache.stream_acc(spec, with_metrics)
    accs = [acc0] * C

    def dispatch_direct(jobs):
        # whole-slot partition per cell (the direct call's wave
        # partition), then greedy physical packing up to max_wave
        slots = []
        for ci, sd, reps in jobs:
            lo = 0
            while lo < reps:
                n = min(cell_wave, reps - lo)
                slots.append((ci, sd, lo, n))
                lo += n
        waves, cur, lanes = [], [], 0
        for s in slots:
            if cur and lanes + s[3] > max_wave:
                waves.append(cur)
                cur, lanes = [], 0
            cur.append(s)
            lanes += s[3]
        if cur:
            waves.append(cur)
        from cimba_tpu.core.loop import drive_chunks

        for wslots in waves:
            sizes = tuple(n for _, _, _, n in wslots)
            live = sum(sizes)
            pad = _wave_shape(live, unit, pad_waves, max_wave) - live
            reps_c = [
                jnp.arange(lo, lo + n) for _, _, lo, n in wslots
            ]
            seeds_c = [
                ex._seed_column(sd, n) for _, sd, _, n in wslots
            ]
            if t_end is None and pad == 0:
                # no horizon and no pads: omit the t_stop leaf like the
                # direct stream path (the cheap chunk cond)
                ts_c = None
            else:
                ts_c = [
                    ex._horizon_column(t_end, n)
                    for _, _, _, n in wslots
                ]
            pws_c = [
                jax.tree.map(
                    lambda x: jnp.broadcast_to(
                        jnp.asarray(x), (n,) + jnp.shape(x)
                    ),
                    rows[ci],
                )
                for ci, _, _, n in wslots
            ]
            if pad:
                # dead masked lanes (t_stop=-inf): never dispatch an
                # event, sliced off before every fold; params are the
                # lead cell's row so user_init sees valid values
                reps_c.append(jnp.zeros((pad,), reps_c[0].dtype))
                seeds_c.append(ex._seed_column(0, pad))
                ts_c.append(jnp.full((pad,), -jnp.inf, ts_c[0].dtype))
                pws_c.append(jax.tree.map(
                    lambda x: jnp.broadcast_to(
                        jnp.asarray(x), (pad,) + jnp.shape(x)
                    ),
                    rows[wslots[0][0]],
                ))
            if len(reps_c) == 1:
                reps_cat, seed_cat, pw_cat = (
                    reps_c[0], seeds_c[0], pws_c[0]
                )
                ts_cat = None if ts_c is None else ts_c[0]
            else:
                reps_cat = jnp.concatenate(reps_c)
                seed_cat = jnp.concatenate(seeds_c)
                ts_cat = None if ts_c is None else jnp.concatenate(ts_c)
                pw_cat = jax.tree.map(
                    lambda *xs: jnp.concatenate(xs, axis=0), *pws_c
                )
            sims = init_j(reps_cat, seed_cat, ts_cat, pw_cat)
            sims = drive_chunks(
                chunk_j, sims, poll_every=poll_every, on_chunk=on_chunk
            )
            # slot-keyed fold: slice each cell's contiguous slot off
            # the wave (data movement only) and fold it through the ONE
            # shared fold program, in (cell, lo) order — the exact
            # merge sequence of that cell's direct stream call.  Pad
            # lanes sit past the last slot's offset and never fold.
            off = 0
            for ci, _, _, n in wslots:
                sl = jax.tree.map(
                    lambda x, off=off, n=n: x[off : off + n], sims
                )
                accs[ci] = fold_j(accs[ci], sl)
                off += n
            sims = None  # one-wave peak memory, like the stream runner
            occ["waves"] += 1
            occ["lanes_live"] += live
            occ["lanes_padded"] += pad
            for ci, _, _, _ in wslots:
                occ["slots_by_cell"][ci] += 1

    def dispatch_serve(jobs, round_):
        from cimba_tpu.serve.service import Request

        handles = []
        for ci, sd, reps in jobs:
            handles.append((ci, service.submit(Request(
                spec, rows[ci], reps, seed=sd, t_end=t_end, pack=pack,
                chunk_steps=chunk_steps,
                wave_size=min(cell_wave, reps),
                summary_path=summary_path,
                label=f"{grid.name}:{grid.cell_label(ci)}:r{round_}",
            ))))
        merge_j = _pcache.cached(
            programs, ("sweep_serve_merge",),
            lambda: jax.jit(_serve_merge),
        )
        for ci, h in handles:
            res = h.result(serve_timeout)
            accs[ci] = merge_j(
                accs[ci], res.summary, res.n_failed, res.total_events,
                res.metrics if with_metrics else None,
            )

    aud = _obs_audit.resolve(audit)
    seed_log: list = [[] for _ in range(C)] if aud is not None else []

    live = np.ones(C, bool)
    n_reps = np.zeros(C, np.int64)
    stop_round = np.full(C, -1, np.int32)
    n_rounds = 0
    total_rounds = 1 if stop is None else int(max_rounds)
    rep_cap = max(R0, max_wave)
    try:
        while n_rounds < total_rounds and live.any():
            live_cells = np.flatnonzero(live)
            if stop is not None and redistribute:
                reps_r = min(max(R0, R0 * C // len(live_cells)), rep_cap)
            else:
                reps_r = R0
            jobs = [
                (int(c), round_seed(seed, int(c), n_rounds), reps_r)
                for c in live_cells
            ]
            if aud is not None:
                for c, sd, _ in jobs:
                    seed_log[c].append(int(sd))
            span_round = None
            if rec is not None:
                span_round = rec.start(
                    trace, "round", parent=root, round=n_rounds,
                    n_live=len(live_cells), reps_per_cell=reps_r,
                )
            if service is None:
                dispatch_direct(jobs)
            else:
                dispatch_serve(jobs, n_rounds)
            for c, _, n in jobs:
                n_reps[c] += n
            n_rounds += 1
            if stop is not None:
                met_now = stop.met(_stack_summaries(accs), n_reps)
                newly = live & met_now
                stop_round[np.flatnonzero(newly)] = n_rounds - 1
                live &= ~met_now
            else:
                live[:] = False
            if span_round is not None:
                rec.end(span_round, outcome="ok",
                        still_live=int(live.sum()))
            if telemetry is not None:
                telemetry.tick("sweep.round")
            if on_round is not None:
                on_round(n_rounds, int(live.sum()), int(n_reps.sum()))
    except BaseException:
        if rec is not None:
            rec.end_trace(trace, "error")
        raise
    if rec is not None:
        rec.end_trace(trace, "completed", rounds=n_rounds)

    confidence = 0.95 if stop is None else stop.confidence
    from cimba_tpu.sweep.adaptive import _halfwidths_jit

    summaries = _stack_summaries(accs)
    hw = np.asarray(_halfwidths_jit(confidence)(summaries), np.float64)
    met = None if stop is None else stop.met(summaries, n_reps)
    metrics = None
    if with_metrics:
        mmerge_j = _pcache.cached(
            programs, ("sweep_metrics_merge",),
            lambda: jax.jit(_metrics.merge),
        )
        metrics = accs[0][3]
        for a in accs[1:]:
            metrics = mmerge_j(metrics, a[3])
    occ["slots_by_cell"] = occ["slots_by_cell"].tolist()
    lanes = occ["lanes_live"] + occ["lanes_padded"]
    occ["padding_waste_frac"] = (
        occ["lanes_padded"] / lanes if lanes else 0.0
    )
    if serve_stats0 is not None:
        s1 = service.stats()
        occ["serve"] = {
            k: s1[k] - serve_stats0[k]
            for k in ("batches", "waves", "lanes_dispatched",
                      "lanes_padded")
        }
    audit_card = None
    if aud is not None:
        from cimba_tpu import config as _config

        cells_blk = [
            {
                "cell": grid.cell_label(c),
                "seeds": seed_log[c],
                "reps": int(n_reps[c]),
                "stop_round": int(stop_round[c]),
                "result_digest": _obs_audit.result_digest(accs[c]),
            }
            for c in range(C)
        ]
        audit_card = aud.finalize(
            "sweep",
            spec=spec,
            seed_schedule={
                "seed": int(seed),
                "rule": "round_seed(seed, cell, round)",
            },
            geometry={
                "grid": grid.name,
                "n_cells": C,
                "reps_per_cell": R0,
                "cell_wave": cell_wave,
                "max_wave": max_wave,
                "chunk_steps": chunk_steps,
                "t_end": t_end,
                "profile": _config.active_profile(),
                "with_metrics": with_metrics,
                "adaptive": stop is not None,
                "redistribute": bool(redistribute),
                "n_rounds": n_rounds,
                "serve_backed": service is not None,
            },
            cells=cells_blk,
            schedule=sched_block,
            telemetry=(
                telemetry.snapshot() if telemetry is not None else None
            ),
        )
    return SweepResult(
        grid=grid,
        summaries=summaries,
        n_reps=n_reps,
        n_failed=np.asarray(
            [int(a[1]) for a in accs], np.int64
        ),
        total_events=np.asarray(
            [int(a[2]) for a in accs], np.int64
        ),
        stop_round=stop_round,
        halfwidth=hw,
        met=met,
        n_rounds=n_rounds,
        seed=seed,
        confidence=confidence,
        wall_s=time.perf_counter() - t0,
        occupancy=occ,
        metrics=metrics,
        audit=audit_card,
    )


def run_fused_sweeps(
    points,
    *,
    reps_per_cell: int,
    seed: int = 0,
    service=None,
    fuse_max_specs: Optional[int] = None,
    max_wave: int = 4096,
    serve_timeout: float = 600.0,
    **kw,
) -> list:
    """Run several DISTINCT-model sweeps through one shared
    fuse-enabled service, so their cells pack into cross-spec fused
    waves (docs/26_wave_fusion.md) instead of each model degenerating
    to its own mostly-padded waves.

    ``points`` is a sequence of ``(spec, grid)`` pairs; each runs as a
    serve-backed :func:`run_sweep` with the SAME ``reps_per_cell`` /
    ``seed`` / forwarded ``**kw``, concurrently, against one
    :class:`~cimba_tpu.serve.service.Service` with ``fuse=True`` —
    compatible-shape specs land in one fusion class and their
    (cell, round) requests splice into shared superprogram waves;
    shape-incompatible specs simply serve unfused (fusion never
    changes results, only packing).  Returns the per-point
    :class:`SweepResult` list in ``points`` order — every per-cell
    result stays bitwise the direct fixed-R call's, exactly as the
    serve-backed single-sweep contract pins.

    Pass ``service=`` to reuse a caller-owned service (its ``fuse``
    setting then governs; the per-call knobs are ignored) — e.g. to
    fuse sweep traffic with live serving traffic."""
    import threading

    points = list(points)
    if not points:
        return []
    owned = service is None
    if owned:
        from cimba_tpu.serve.service import Service

        service = Service(
            max_wave=max_wave, fuse=True,
            fuse_max_specs=fuse_max_specs,
        )
    results: list = [None] * len(points)
    errors: list = [None] * len(points)

    def one(i, spec, grid):
        try:
            results[i] = run_sweep(
                spec, grid, reps_per_cell=reps_per_cell, seed=seed,
                service=service, serve_timeout=serve_timeout,
                max_wave=max_wave, **kw,
            )
        except BaseException as e:  # re-raised on the caller thread
            errors[i] = e

    try:
        threads = [
            threading.Thread(
                target=one, args=(i, s, g), daemon=True,
                name=f"fused-sweep-{i}",
            )
            for i, (s, g) in enumerate(points)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        if owned:
            service.shutdown(wait=True)
    for e in errors:
        if e is not None:
            raise e
    return results
